"""Decision observability (ISSUE 13, simtpu/explain):

- failure breakdown: per-stage elimination counts + feasible survivors
  sum to the valid node count for EVERY unplaced pod on a fuzz-generated
  gnarly case, bit-equal between the jitted pass and the pure-numpy twin
  (SIMTPU_EXPLAIN_JIT=0), with the rendered status string's first-failing
  stage agreeing with the legacy REASON_TEXT reason bit-for-bit;
- the cascade-order pin: STAGES mirrors engine/scan.FILTER_CASCADE and
  StepEval.fail_code, and every FAIL_* code has a REASON_TEXT entry (the
  exhaustiveness guard making `_record_failed`'s fallback unreachable);
- the off path is zero-cost: a placement without --explain bumps no
  explain.* instrument and traces no compile.explain executable;
- score attribution: recomputed argmax == recorded landing node
  (prefix-state exactness), all plugins present, margin >= 0;
- bottleneck: a cpu-starved problem names cpu as binding and sizes the
  deficit in template nodes;
- surfaces: simulate(explain=), the three planners' explain blocks, the
  `simtpu explain` subcommand, and --explain on apply --json.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from simtpu.core.tensorize import Tensorizer
from simtpu.engine.scan import (
    FILTER_CASCADE,
    OK,
    REASON_TEXT,
    Engine,
    StepEval,
)
from simtpu.explain import (
    STAGES,
    attribute_scores,
    bottleneck_analysis,
    explain_failures,
)
from simtpu.obs.metrics import REGISTRY
from simtpu.synth import make_deployment, make_node, synth_apps, synth_cluster
from simtpu.workloads.expand import get_valid_pods_exclude_daemonset


def _expand(apps):
    pods = []
    for a in apps:
        pods.extend(get_valid_pods_exclude_daemonset(a.resource))
    return pods


def _place(cluster, pods, factory=Engine):
    tz = Tensorizer(cluster.nodes, storage_classes=cluster.storage_classes)
    eng = factory(tz)
    batch = tz.add_pods(pods)
    nodes, reasons, extras = eng.place(batch)
    return tz, eng, batch, np.asarray(nodes), np.asarray(reasons), extras


@pytest.fixture(scope="module")
def gnarly():
    """A fuzz-generated gnarly case (the audit fuzzer's generator) made
    infeasible on several axes: hard anti-affinity pressure plus a fat
    deployment no node can hold."""
    from simtpu.audit.fuzz import gen_case

    cluster, apps, _mix = gen_case(seed=5, n_nodes=12, n_pods=72)
    apps[0].resource.deployments.append(
        make_deployment("fat-cpu", 3, 10_000_000, 8)
    )
    return _place(cluster, _expand(apps))


class TestCascadeOrderPin:
    def test_stages_mirror_filter_cascade(self):
        """The explain stage table IS FILTER_CASCADE (field names
        shortened) — the breakdown's first-failing stage and
        StepEval.fail_code can never drift."""
        assert len(STAGES) == len(FILTER_CASCADE)
        for (key, code), (field, fcode) in zip(STAGES, FILTER_CASCADE):
            assert code == fcode
            assert field == ("m_all" if key == "interpod" else f"m_{key}")
        assert set(f for f, _ in FILTER_CASCADE) <= set(StepEval._fields)

    def test_reason_text_exhaustive(self):
        """Every FAIL_* code renders a real reason — the guard that makes
        `Simulator._record_failed`'s "unschedulable" fallback (and the
        incremental planner's copy) unreachable."""
        import simtpu.engine.scan as scan

        codes = {
            v for k, v in vars(scan).items()
            if k.startswith("FAIL_") and isinstance(v, int)
        }
        assert codes == set(REASON_TEXT)
        assert OK not in REASON_TEXT

    def test_fail_code_is_first_empty_stage(self):
        """StepEval.fail_code == the first FILTER_CASCADE stage whose
        mask is empty, on every single-empty-stage combination."""
        import jax.numpy as jnp

        n = 4
        fields = [f for f, _ in FILTER_CASCADE]
        for empty_at in range(len(fields)):
            masks = {}
            for s, f in enumerate(fields):
                masks[f] = jnp.zeros(n, bool) if s >= empty_at else jnp.ones(n, bool)
            ev = StepEval(
                **masks,
                score=jnp.zeros(n),
                score_nostorage=jnp.zeros(n),
                lvm_alloc=jnp.zeros((n, 1)),
                dev_take=jnp.zeros((n, 1), bool),
                gpu_shares=jnp.zeros((n, 1)),
            )
            assert int(ev.fail_code()) == FILTER_CASCADE[empty_at][1]


class TestFailureBreakdown:
    def test_counts_sum_to_n_and_match_numpy_oracle(self, gnarly, monkeypatch):
        """The acceptance pin: for EVERY unplaced pod of the gnarly case,
        per-stage elimination counts (+ feasible survivors) sum to N, and
        the jitted pass is bit-equal to the pure-numpy twin — counts,
        survivors, witnesses, and fail codes."""
        tz, eng, batch, nodes, reasons, _ = gnarly
        tensors = tz.freeze()
        unp = np.flatnonzero(nodes < 0)
        assert len(unp) >= 3, "the gnarly case must actually strand pods"
        state = eng.carried_state()
        bd = explain_failures(tensors, batch, unp, state, reasons=reasons)
        assert bd.mode == "jit"
        n = tensors.alloc.shape[0]
        assert bd.n_nodes == n
        total = bd.counts.sum(axis=1) + bd.feasible
        assert np.array_equal(total, np.full(len(unp), n)), (
            bd.counts, bd.feasible
        )
        monkeypatch.setenv("SIMTPU_EXPLAIN_JIT", "0")
        twin = explain_failures(tensors, batch, unp, state, reasons=reasons)
        assert twin.mode == "numpy"
        assert np.array_equal(bd.counts, twin.counts)
        assert np.array_equal(bd.feasible, twin.feasible)
        assert np.array_equal(bd.fail_code, twin.fail_code)
        assert np.array_equal(bd.witnesses, twin.witnesses)

    def test_witnesses_are_eliminated_nodes(self, gnarly):
        tz, eng, batch, nodes, reasons, _ = gnarly
        tensors = tz.freeze()
        unp = np.flatnonzero(nodes < 0)
        state = eng.carried_state()
        bd = explain_failures(tensors, batch, unp, state, reasons=reasons)
        k = bd.witnesses.shape[2]
        for i in range(len(bd)):
            for s in range(len(STAGES)):
                wit = bd.witnesses[i, s]
                real = wit[wit >= 0]
                # as many witnesses as eliminations, up to the cap, all
                # valid node indices, strictly ascending (lowest-first)
                assert len(real) == min(int(bd.counts[i, s]), k)
                assert np.all(real < bd.n_nodes)
                assert np.all(np.diff(real) > 0)

    def test_status_first_failing_stage_is_legacy_reason(self):
        """A pod that fails AFTER everything else placed (end state ==
        attempt state): the recorded fail code equals the breakdown's
        first-failing stage, and the rendered status entry for it is the
        REASON_TEXT string bit-for-bit — the legacy headline, now with a
        count in front."""
        cluster = synth_cluster(6, seed=11, zones=2, taint_frac=0.0)
        apps = synth_apps(12, seed=12, zones=2, pods_per_deployment=6)
        apps[-1].resource.deployments.append(
            make_deployment("zz-fat", 1, 10_000_000, 8)
        )
        tz, eng, batch, nodes, reasons, _ = _place(cluster, _expand(apps))
        tensors = tz.freeze()
        unp = np.flatnonzero(nodes < 0)
        assert len(unp) == 1
        state = eng.carried_state()
        bd = explain_failures(tensors, batch, unp, state, reasons=reasons)
        assert int(bd.fail_code[0]) == int(reasons[unp[0]])
        assert bd.headline(0) == REASON_TEXT[int(reasons[unp[0]])]
        # the first failing stage = the LAST stage in cascade order with
        # a nonzero elimination count; its status entry is
        # "<count> <REASON_TEXT>" verbatim
        nz = [s for s in range(len(STAGES)) if bd.counts[0, s] > 0]
        first_fail = nz[-1]
        assert STAGES[first_fail][1] == int(bd.fail_code[0])
        expected = f"{int(bd.counts[0, first_fail])} {REASON_TEXT[STAGES[first_fail][1]]}"
        assert expected in bd.status(0)
        assert bd.status(0).startswith(f"0/{bd.n_nodes} nodes are available: ")
        assert int(bd.feasible[0]) == 0

    def test_forced_pod_status_reports_recorded_reason(self):
        """A spec.nodeName pod pinned to a node outside the cluster never
        ran the cascade: zero stage counts on a non-empty cluster must
        render the recorded reason — not 'no nodes in the cluster',
        which would be false on a cluster that has nodes."""
        from simtpu.engine.scan import FAIL_NO_NODE

        cluster = synth_cluster(4, seed=81, zones=2)
        apps = synth_apps(4, seed=82, zones=2, pods_per_deployment=2)
        pods = _expand(apps)
        pods[0]["spec"]["nodeName"] = "no-such-node"
        tz, eng, batch, nodes, reasons, _ = _place(cluster, pods)
        tensors = tz.freeze()
        unp = np.flatnonzero(nodes < 0)
        bd = explain_failures(
            tensors, batch, unp, eng.carried_state(), reasons=reasons
        )
        idx = [i for i in range(len(bd)) if int(bd.reasons[i]) == FAIL_NO_NODE]
        assert idx, "the forced pod must strand with FAIL_NO_NODE"
        i = idx[0]
        assert bd.counts[i].sum() == 0 and int(bd.feasible[i]) == 0
        assert REASON_TEXT[FAIL_NO_NODE] in bd.status(i)
        assert "no nodes in the cluster" not in bd.status(i)

    def test_groups_cap_reported_not_silent(self, gnarly):
        tz, eng, batch, nodes, reasons, _ = gnarly
        tensors = tz.freeze()
        unp = np.flatnonzero(nodes < 0)
        state = eng.carried_state()
        bd = explain_failures(tensors, batch, unp, state, reasons=reasons)
        doc = bd.to_doc(top=1)
        assert len(doc["groups"]) == 1
        distinct = len(
            {
                (int(bd.reasons[i]), tuple(map(int, bd.counts[i])))
                for i in range(len(bd))
            }
        )
        if distinct > 1:
            assert doc["truncated_groups"] == distinct - 1
        assert doc["version"] >= 1
        assert doc["unplaced"] == len(unp)


class TestOffPathZeroCost:
    def test_no_explain_instruments_without_request(self):
        """The acceptance pin for the off path: an ordinary placement
        (explain never requested) bumps no explain.* instrument and
        traces no compile.explain executable — pinned via registry
        deltas, the same counters that account every device dispatch."""
        cluster = synth_cluster(6, seed=31, zones=2)
        apps = synth_apps(18, seed=32, zones=2, pods_per_deployment=6)
        before = REGISTRY.snapshot()
        _place(cluster, _expand(apps))
        delta = REGISTRY.delta_since(before)
        for name, v in delta.items():
            if name.startswith("explain.") or name == "compile.explain":
                base = before.get(name)
                assert v == 0 or v == base or (
                    isinstance(v, dict) and v.get("count") == 0
                ), f"{name} moved without --explain: {v}"

    def test_simulate_without_explain_attaches_nothing(self):
        from simtpu.api import simulate
        from simtpu.core.objects import ResourceTypes

        cluster = synth_cluster(4, seed=33, zones=2)
        trial = ResourceTypes(**{k: list(v) for k, v in vars(cluster).items()})
        trial.pods = _expand(synth_apps(6, seed=34, zones=2, pods_per_deployment=3))
        result = simulate(trial)
        assert result.explain is None


class TestScoreAttribution:
    def test_argmax_matches_recorded_and_all_plugins_present(self):
        cluster = synth_cluster(8, seed=41, zones=2, taint_frac=0.1)
        apps = synth_apps(
            24, seed=42, zones=2, pods_per_deployment=8,
            anti_affinity_frac=0.3, spread_frac=0.4, selector_frac=0.3,
        )
        tz, eng, batch, nodes, reasons, extras = _place(cluster, _expand(apps))
        tensors = tz.freeze()
        docs = attribute_scores(tensors, batch, nodes, extras, max_pods=6)
        assert 0 < len(docs) <= 6
        plugins = {
            "NodeResourcesLeastAllocated", "NodeResourcesBalancedAllocation",
            "Simon", "Open-Gpu-Share", "NodeAffinity", "TaintToleration",
            "InterPodAffinity", "PodTopologySpread", "SelectorSpread",
            "ImageLocality", "NodePreferAvoidPods", "Open-Local",
        }
        for d in docs:
            assert d["consistent"], d
            assert d["winner"] == d["node"]
            assert {t["plugin"] for t in d["terms"]} == plugins
            if d["margin"] is not None:
                assert d["margin"] >= 0

    def test_extras_from_log_round_trip(self):
        from simtpu.explain import extras_from_log

        cluster = synth_cluster(6, seed=43, zones=2)
        apps = synth_apps(12, seed=44, zones=2, pods_per_deployment=4)
        tz, eng, batch, nodes, reasons, extras = _place(cluster, _expand(apps))
        tensors = tz.freeze()
        rebuilt = extras_from_log(tensors, nodes, eng.ext_log)
        for key in ("lvm_alloc", "dev_take", "gpu_shares"):
            assert np.array_equal(
                np.asarray(rebuilt[key]), np.asarray(extras[key])
            ), key


class TestBottleneck:
    def test_cpu_starved_names_cpu_binding_and_sizes_template(self):
        cluster = synth_cluster(4, seed=51, zones=2)
        apps = synth_apps(8, seed=52, zones=2, pods_per_deployment=4)
        # 6 pods of 48 cores each against a small cluster: cpu-binding
        apps[0].resource.deployments.append(
            make_deployment("hungry", 6, 48000, 1)
        )
        tz, eng, batch, nodes, reasons, _ = _place(cluster, _expand(apps))
        tensors = tz.freeze()
        unp = np.flatnonzero(nodes < 0)
        assert len(unp) >= 1
        template = make_node("tmpl", 64000, 128, {"kubernetes.io/hostname": "tmpl"})
        doc = bottleneck_analysis(
            tensors, batch, nodes, reasons, new_node=template,
            free=np.asarray(eng.carried_state().free),
        )
        assert doc["unplaced"] == len(unp)
        assert doc["binding"]["resource"] == "cpu"
        assert doc["capacity_shaped"] >= 1
        tpl = doc["template"]
        assert tpl["helpable"] >= 1
        assert tpl.get("template_nodes_hint", 0) >= 1

    def test_stateless_doc_free_override_wins(self):
        """build_explain_doc(state=None, free=...): a caller that can see
        more placements than `nodes_arr` covers (the incremental
        planner's checkpoint-replayed probe candidates, whose sliced
        batch hides the base run's consumption) supplies the full free
        matrix — the bottleneck must use it, not re-derive an overstated
        one from the slice."""
        from simtpu.explain import build_explain_doc

        cluster = synth_cluster(4, seed=55, zones=2)
        apps = synth_apps(8, seed=56, zones=2, pods_per_deployment=4)
        apps[0].resource.deployments.append(
            make_deployment("fat", 2, 10_000_000, 4)
        )
        tz, eng, batch, nodes, reasons, _ = _place(cluster, _expand(apps))
        tensors = tz.freeze()
        unp = np.flatnonzero(nodes < 0)
        assert len(unp) >= 1
        exhausted = np.zeros_like(np.asarray(tensors.alloc))
        doc = build_explain_doc(
            tensors, batch, unp, None, nodes, reasons, free=exhausted
        )
        assert "failures" not in doc  # no carry, breakdown degrades away
        for res in doc["bottleneck"]["resources"]:
            assert res["free"] == 0.0, res
        # and without the override the slice-derived free is nonzero
        doc2 = build_explain_doc(tensors, batch, unp, None, nodes, reasons)
        assert any(r["free"] > 0 for r in doc2["bottleneck"]["resources"])

    def test_empty_unplaced_set_is_empty_doc(self):
        cluster = synth_cluster(4, seed=53, zones=2)
        apps = synth_apps(6, seed=54, zones=2, pods_per_deployment=3)
        tz, eng, batch, nodes, reasons, _ = _place(cluster, _expand(apps))
        assert bottleneck_analysis(tz.freeze(), batch, nodes, reasons) == {}


class TestSurfaces:
    def test_simulate_explain_block(self):
        from simtpu.api import simulate
        from simtpu.core.objects import ResourceTypes

        cluster = synth_cluster(4, seed=61, zones=2)
        apps = synth_apps(6, seed=62, zones=2, pods_per_deployment=3)
        apps[0].resource.deployments.append(
            make_deployment("fat", 2, 10_000_000, 4)
        )
        trial = ResourceTypes(**{k: list(v) for k, v in vars(cluster).items()})
        trial.pods = _expand(apps)
        result = simulate(trial, explain=True)
        doc = result.explain
        assert doc and doc["failures"]["unplaced"] == len(result.unscheduled_pods)
        groups = doc["failures"]["groups"]
        assert groups and all("status" in g for g in groups)
        # the headline stays the legacy reason: each group's reason text
        # appears verbatim inside the recorded UnscheduledPod reason
        by_reason = {g["reason"] for g in groups}
        assert any(
            any(r in u.reason for r in by_reason)
            for u in result.unscheduled_pods
        )
        assert doc["bottleneck"]["unplaced"] >= 1

    def test_plan_capacity_failure_carries_explain(self):
        from simtpu.plan.capacity import plan_capacity

        cluster = synth_cluster(3, seed=63, zones=2)
        apps = synth_apps(4, seed=64, zones=2, pods_per_deployment=2)
        apps[0].resource.deployments.append(
            make_deployment("fat", 2, 10_000_000, 4)
        )
        template = make_node("tmpl", 4000, 8, {"kubernetes.io/hostname": "tmpl"})
        plan = plan_capacity(
            cluster, apps, template, max_new_nodes=3, explain=True, audit=False
        )
        assert not plan.success
        assert plan.explain, "a failing explained plan must carry the block"
        assert plan.explain.get("bottleneck", {}).get("unplaced", 0) >= 1

    def test_plan_capacity_incremental_failure_carries_explain(self):
        from simtpu.plan.incremental import plan_capacity_incremental

        cluster = synth_cluster(3, seed=65, zones=2)
        apps = synth_apps(4, seed=66, zones=2, pods_per_deployment=2)
        apps[0].resource.deployments.append(
            make_deployment("fat", 2, 10_000_000, 4)
        )
        template = make_node("tmpl", 4000, 8, {"kubernetes.io/hostname": "tmpl"})
        plan = plan_capacity_incremental(
            cluster, apps, template, max_new_nodes=3, explain=True, audit=False
        )
        assert not plan.success
        assert plan.explain
        bn = plan.explain.get("bottleneck", {})
        assert bn.get("unplaced", 0) >= 1
        assert "failures" in plan.explain
        # the what-to-buy verdict rides the template block
        assert "template" in bn

    def test_plan_resilience_failure_carries_explain(self):
        from simtpu.plan.resilience import plan_resilience

        cluster = synth_cluster(3, seed=67, zones=2)
        apps = synth_apps(6, seed=68, zones=2, pods_per_deployment=3)
        apps[0].resource.deployments.append(
            make_deployment("fat", 2, 10_000_000, 4)
        )
        plan = plan_resilience(
            cluster, apps, new_node=None, spec="k=1", explain=True, audit=False
        )
        assert not plan.success
        assert plan.explain
        assert plan.explain.get("bottleneck", {}).get("unplaced", 0) >= 1

    @pytest.mark.slow
    def test_cli_explain_subcommand_json(self, capsys):
        from simtpu.cli import main

        rc = main([
            "explain", "-f", "examples/simtpu-config.yaml", "--json",
            "--scores", "2",
        ])
        out = capsys.readouterr().out
        assert rc == 0
        doc = json.loads(out)
        assert doc["version"] >= 1
        assert doc["placed"] + doc["unplaced"] == doc["pods"]
        assert len(doc.get("scores") or []) <= 2
        for s in doc.get("scores") or []:
            assert s["consistent"]

    @pytest.mark.slow
    def test_cli_apply_explain_json_and_off_default(self, capsys):
        from simtpu.cli import main

        rc = main([
            "apply", "-f", "examples/simtpu-config.yaml", "--json",
            "--explain", "--no-audit",
        ])
        out = capsys.readouterr().out
        assert rc in (0, 1)
        doc = json.loads(out)
        # a feasible plan explains nothing (no unplaced pods) — the block
        # is version-only or absent; an infeasible one carries failures
        if "explain" in doc:
            assert doc["explain"]["version"] >= 1

    def test_explain_report_renders(self):
        from simtpu.report import explain_report

        cluster = synth_cluster(4, seed=71, zones=2)
        apps = synth_apps(6, seed=72, zones=2, pods_per_deployment=3)
        apps[0].resource.deployments.append(
            make_deployment("fat", 2, 10_000_000, 4)
        )
        tz, eng, batch, nodes, reasons, extras = _place(cluster, _expand(apps))
        tensors = tz.freeze()
        unp = np.flatnonzero(nodes < 0)
        bd = explain_failures(
            tensors, batch, unp, eng.carried_state(), reasons=reasons
        )
        doc = {
            "version": 1,
            "failures": bd.to_doc(),
            "bottleneck": bottleneck_analysis(
                tensors, batch, nodes, reasons,
                free=np.asarray(eng.carried_state().free),
            ),
            "scores": attribute_scores(tensors, batch, nodes, extras, max_pods=2),
        }
        text = explain_report(doc)
        assert "Why Unschedulable" in text
        assert "Bottleneck" in text
        assert "Score Attribution" in text
        assert "nodes are available" in text
