"""Test fixture factory — functional-options builders for k8s objects.

Python port of `pkg/test/*.go` (MakeFakeNode, MakeFakePod, MakeFakeDeployment,
MakeFakeStatefulSet, MakeFakeDaemonSet, MakeFakeReplicaSet, MakeFakeJob,
MakeFakeCronJob and their With* options). Builders return plain manifest dicts.
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List


def _resources(cpu: str, memory: str) -> dict:
    req = {}
    if cpu:
        req["cpu"] = cpu
    if memory:
        req["memory"] = memory
    return {"requests": req} if req else {}


def _container(cpu: str, memory: str) -> dict:
    c = {"name": "container", "image": "nginx"}
    res = _resources(cpu, memory)
    if res:
        c["resources"] = res
    return c


def make_fake_node(name: str, cpu: str, memory: str, *opts: Callable) -> dict:
    node = {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "labels": {}, "annotations": {}},
        "spec": {},
        "status": {
            "allocatable": {"cpu": cpu, "memory": memory, "pods": "110"},
            "capacity": {"cpu": cpu, "memory": memory, "pods": "110"},
        },
    }
    for opt in opts:
        opt(node)
    return node


def with_node_labels(labels: Dict[str, str]) -> Callable:
    def opt(node):
        node["metadata"]["labels"].update(labels)

    return opt


def with_node_taints(taints: List[dict]) -> Callable:
    def opt(node):
        node["spec"]["taints"] = taints

    return opt


def with_node_local_storage(storage: dict) -> Callable:
    """storage = {"vgs": [...], "devices": [...]} — the reference's
    utils.NodeStorage JSON (`pkg/test/node.go` WithNodeLocalStorage)."""

    def opt(node):
        node["metadata"]["annotations"]["simon/node-local-storage"] = json.dumps(storage)

    return opt


def with_node_allocatable(resources: Dict[str, str]) -> Callable:
    def opt(node):
        node["status"]["allocatable"].update(resources)
        node["status"]["capacity"].update(resources)

    return opt


def make_fake_pod(name: str, namespace: str, cpu: str, memory: str, *opts: Callable) -> dict:
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"containers": [_container(cpu, memory)]},
    }
    for opt in opts:
        opt(pod)
    return pod


def with_pod_node_name(node_name: str) -> Callable:
    def opt(pod):
        pod["spec"]["nodeName"] = node_name

    return opt


def with_pod_labels(labels: Dict[str, str]) -> Callable:
    def opt(pod):
        pod["metadata"]["labels"] = labels

    return opt


def with_pod_annotations(annotations: Dict[str, str]) -> Callable:
    def opt(pod):
        pod["metadata"]["annotations"] = annotations

    return opt


def with_pod_tolerations(tolerations: List[dict]) -> Callable:
    def opt(pod):
        pod["spec"]["tolerations"] = tolerations

    return opt


def with_pod_node_selector(selector: Dict[str, str]) -> Callable:
    def opt(pod):
        pod["spec"]["nodeSelector"] = selector

    return opt


def with_pod_affinity(affinity: dict) -> Callable:
    def opt(pod):
        pod["spec"]["affinity"] = affinity

    return opt


def _workload(kind: str, name: str, namespace: str, cpu: str, memory: str) -> dict:
    return {
        "apiVersion": "apps/v1",
        "kind": kind,
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"template": {"spec": {"containers": [_container(cpu, memory)]}}},
    }


def _template_opt(setter: Callable[[dict], None]) -> Callable:
    def opt(obj):
        setter(obj["spec"]["template"]["spec"])

    return opt


def make_fake_deployment(name, namespace, replicas, cpu, memory, *opts) -> dict:
    d = _workload("Deployment", name, namespace, cpu, memory)
    d["spec"]["replicas"] = replicas
    for opt in opts:
        opt(d)
    return d


def make_fake_replica_set(name, namespace, replicas, cpu, memory, *opts) -> dict:
    rs = _workload("ReplicaSet", name, namespace, cpu, memory)
    rs["spec"]["replicas"] = replicas
    for opt in opts:
        opt(rs)
    return rs


def make_fake_stateful_set(name, namespace, replicas, cpu, memory, *opts) -> dict:
    sts = _workload("StatefulSet", name, namespace, cpu, memory)
    sts["spec"]["replicas"] = replicas
    for opt in opts:
        opt(sts)
    return sts


def make_fake_daemon_set(name, namespace, cpu, memory, *opts) -> dict:
    ds = _workload("DaemonSet", name, namespace, cpu, memory)
    for opt in opts:
        opt(ds)
    return ds


def make_fake_job(name, namespace, completions, cpu, memory, *opts) -> dict:
    job = _workload("Job", name, namespace, cpu, memory)
    job["apiVersion"] = "batch/v1"
    job["kind"] = "Job"
    job["spec"]["completions"] = completions
    for opt in opts:
        opt(job)
    return job


def make_fake_cron_job(name, namespace, completions, cpu, memory, *opts) -> dict:
    job = _workload("Job", name, namespace, cpu, memory)
    cj = {
        "apiVersion": "batch/v1beta1",
        "kind": "CronJob",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"schedule": "* * * * *", "jobTemplate": {"spec": job["spec"]}},
    }
    for opt in opts:
        opt(cj)
    return cj


# template-level options shared by workload kinds (mirror With*Tolerations etc.)
def with_template_tolerations(tolerations: List[dict]) -> Callable:
    return _template_opt(lambda s: s.update({"tolerations": tolerations}))


def with_template_node_selector(selector: Dict[str, str]) -> Callable:
    return _template_opt(lambda s: s.update({"nodeSelector": selector}))


def with_template_affinity(affinity: dict) -> Callable:
    return _template_opt(lambda s: s.update({"affinity": affinity}))


def with_cronjob_template_tolerations(tolerations: List[dict]) -> Callable:
    def opt(cj):
        cj["spec"]["jobTemplate"]["spec"]["template"]["spec"]["tolerations"] = tolerations

    return opt
