"""report.py rendering edge cases (ISSUE 13 satellite — ~400 lines of
table assembly with no dedicated test module until now):

- `render_table`: the column-0 auto-merge (tablewriter's
  SetAutoMergeCellsByColumnIndex([0])) interacting with multi-line cells,
  width computation across embedded newlines, empty-row tables;
- `resilience_report` / `audit_report`: the truncation caps (worst
  scenarios / critical nodes / witness detail) stay caps, not crashes;
- `explain_report`: renders every section and degrades to one line on an
  empty doc.
"""

from __future__ import annotations

from simtpu.report import (
    audit_report,
    explain_report,
    render_table,
    resilience_report,
)


class TestRenderTable:
    def test_empty_rows_renders_header_only(self):
        out = render_table(["A", "Bee"], [])
        lines = out.split("\n")
        # separator, header, separator — nothing else
        assert len(lines) == 3
        assert lines[0] == lines[2]
        assert "A" in lines[1] and "BEE" in lines[1]

    def test_col0_merge_repeats_blanked(self):
        rows = [["n1", "a"], ["n1", "b"], ["n2", "c"], ["n1", "d"]]
        out = render_table(["Node", "Pod"], rows)
        body = [ln for ln in out.split("\n") if ln.startswith("|")]
        # row 2 ("n1", "b") merges col 0; row 4's "n1" is a NEW run and
        # stays (the merge compares adjacent rows only)
        cells0 = [ln.split("|")[1].strip() for ln in body[1:]]
        assert cells0 == ["n1", "", "n2", "n1"]

    def test_col0_merge_off(self):
        rows = [["x", "a"], ["x", "b"]]
        out = render_table(["K", "V"], rows, merge_col0=False)
        body = [ln for ln in out.split("\n") if ln.startswith("|")]
        assert [ln.split("|")[1].strip() for ln in body[1:]] == ["x", "x"]

    def test_multiline_cells_set_height_and_width(self):
        rows = [
            ["n1", "line-one\nline-two-is-much-longer", "z"],
            ["n1", "short", "w"],
        ]
        out = render_table(["Node", "Detail", "X"], rows)
        lines = out.split("\n")
        body = [ln for ln in lines if ln.startswith("|")]
        # first data row renders as TWO physical lines
        assert len(body) == 1 + 2 + 1  # header + 2-line row + 1-line row
        # width follows the longest LINE, not the whole cell
        sep = lines[0]
        assert len("line-two-is-much-longer") + 2 <= max(
            len(part) for part in sep.split("+")
        )
        # the second physical line of the multi-line row keeps the grid:
        # col 0 and col 2 pad with spaces, every line has equal length
        assert len({len(ln) for ln in lines}) == 1

    def test_multiline_cell_in_merge_column(self):
        """A multi-line cell in column 0 merges by FULL value — the next
        row's identical multi-line value blanks entirely."""
        rows = [["a\nb", "1"], ["a\nb", "2"], ["c", "3"]]
        out = render_table(["K", "V"], rows)
        body = [ln for ln in out.split("\n") if ln.startswith("|")]
        # rows: header, 2-line row1, 1-line row2 (merged -> blank), row3
        assert len(body) == 1 + 2 + 1 + 1
        merged_row = body[3]
        assert merged_row.split("|")[1].strip() == ""


class _FakeScenarios:
    def __init__(self, labels):
        self.labels = labels

    def __len__(self):
        return len(self.labels)


class _FakeSweep:
    """Duck-typed stand-in for faults.sweep.SweepResult — exactly the
    surface resilience_report consumes."""

    def __init__(self, n=25):
        self.scenarios = _FakeScenarios(
            tuple(f"node:n-{i:03d}" for i in range(n))
        )
        self.unplaced = [i % 7 for i in range(n)]

    def worst(self, top: int = 10):
        pairs = sorted(
            zip(self.scenarios.labels, self.unplaced), key=lambda kv: -kv[1]
        )
        pairs = [kv for kv in pairs if kv[1] > 0]
        return pairs[:top]

    def critical_nodes(self, top: int = 10):
        return [(f"n-{i:03d}", 7 - i) for i in range(min(top, 6))]


class TestResilienceReport:
    def test_truncation_caps_apply(self):
        sweep = _FakeSweep(25)
        out = resilience_report(sweep, top=3)
        # worst-scenario table capped at 3 data rows
        worst_section = out.split("Worst Scenarios")[1].split(
            "Most Critical Nodes"
        )[0]
        data_rows = [
            ln for ln in worst_section.split("\n")
            if ln.startswith("|") and "SCENARIO" not in ln.upper()
        ]
        assert len(data_rows) == 3
        crit_section = out.split("Most Critical Nodes")[1]
        crit_rows = [
            ln for ln in crit_section.split("\n")
            if ln.startswith("|") and "NODE" not in ln.upper()
        ]
        assert len(crit_rows) == 3

    def test_all_survived_omits_worst_table(self):
        sweep = _FakeSweep(4)
        sweep.unplaced = [0, 0, 0, 0]
        out = resilience_report(sweep)
        assert "Worst Scenarios" not in out
        assert "SURVIVAL" in out  # header row renders uppercased


class TestAuditReport:
    def test_not_run_and_clean_one_liners(self):
        assert audit_report({}) == "Audit: not run (--no-audit)"
        clean = audit_report(
            {"ok": True, "checked": 9, "wall_s": 0.123, "mode": "jit"}
        )
        assert clean.startswith("Audit: clean (9 placements certified")
        assert "\n" not in clean

    def test_detail_rows_render_capped_witnesses(self):
        doc = {
            "ok": False,
            "checked": 5,
            "violations": 2,
            "detail": [
                {
                    "class": "overcommit",
                    "pod": "ns/p1",
                    "node": "n1",
                    "witness": {"cpu": 9, "free": -1},
                },
                {"class": "ports", "pod": "ns/p2", "node": "n2"},
            ],
        }
        out = audit_report(doc)
        assert "Audit: FAILED — 2 violation(s) over 5 placements" in out
        assert "overcommit" in out and "cpu=9" in out

    def test_fallback_and_divergence_sections(self):
        doc = {
            "ok": False,
            "fallback": True,
            "fallback_audit": {"ok": True},
            "checked": 3,
            "violations": 1,
            "divergence": {
                "divergent_pods": 1,
                "first_divergent_pod": "ns/p",
                "state_planes": ["free: max|d|=1", "cnt_match: max|d|=2"],
            },
        }
        out = audit_report(doc)
        assert "PRIMARY ENGINE DIVERGED" in out
        assert "serial-exact fallback certified" in out
        assert "differing state planes: free: max|d|=1; cnt_match: max|d|=2" in out


class TestExplainReport:
    def test_empty_doc_degrades_to_one_line(self):
        assert explain_report({}) == (
            "Explain: nothing to explain (no unplaced pods selected)"
        )
        assert explain_report({"version": 1}) == (
            "Explain: nothing to explain (no unplaced pods selected)"
        )

    def test_sections_render_from_doc(self):
        doc = {
            "version": 1,
            "failures": {
                "unplaced": 2,
                "n_nodes": 5,
                "mode": "jit",
                "truncated_groups": 3,
                "groups": [
                    {
                        "pods": 2,
                        "example": "ns/p",
                        "reason": "r",
                        "status": "0/5 nodes are available: 5 x.",
                        "stages": {"static": 3, "res": 2},
                        "witnesses": {"static": ["n1", "n2"]},
                        "feasible": 0,
                    }
                ],
            },
            "bottleneck": {
                "capacity_shaped": 1,
                "constraint_shaped": 1,
                "resources": [
                    {
                        "resource": "cpu",
                        "requested": 12.0,
                        "free": 1.0,
                        "share": 12.0,
                        "fragmented": True,
                    }
                ],
                "binding": {"resource": "cpu", "requested": 12.0, "free": 1.0},
                "template": {
                    "probed": 2,
                    "helpable": 1,
                    "never_helpable": 1,
                    "never_reason": "taints",
                    "template_nodes_hint": 4,
                },
            },
            "scores": [
                {
                    "pod": "ns/q",
                    "node": "n1",
                    "runner_up": "n2",
                    "margin": 1.5,
                    "consistent": True,
                    "terms": [
                        {"plugin": "Simon", "weight": 1.0, "delta": 0.5},
                        {"plugin": "SelectorSpread", "weight": 1.0, "delta": -1.0},
                    ],
                }
            ],
        }
        out = explain_report(doc)
        assert "Why Unschedulable (2 pod(s), 5 node(s), jit pass)" in out
        assert "3 more failure shape(s)" in out
        assert "binding constraint: cpu" in out
        assert "4 template node(s)" in out
        assert "Score Attribution" in out
        assert "SelectorSpread: -1" in out
