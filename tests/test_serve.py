"""`simtpu serve` tests (simtpu/serve, ISSUE 14).

The load-bearing pins:

- ROBUSTNESS MATRIX: an over-deadline request answers a structured 504
  while concurrent requests complete; a full queue answers 429 without
  touching in-flight work; an injected RESOURCE_EXHAUSTED during a
  served dispatch rides the chunk-halving backoff to the correct answer
  (and the exhausted case degrades to 503 + eviction, daemon alive);
  kill -9 + restart rehydrates the session bit-identically from its
  checkpoint; SIGTERM drains in-flight work and exits 0.
- COALESCING: a burst of K sweep-shaped queries against one snapshot
  fuses into ONE vmapped dispatch — pinned via the serve.coalesced and
  fetch.* registry counters — and every coalesced answer is
  bit-identical to the serial one-query-at-a-time oracle.
- BIT-IDENTITY: a served fit answer equals the one-shot `simulate()`
  run with the same name-stream seed, placements included, audit-clean.
- ZERO OFF-PATH COST: no CLI path imports simtpu.serve unless `serve`
  is invoked (the explain off-path pin pattern).
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from simtpu.durable.deadline import RunControl
from simtpu.obs.metrics import REGISTRY
from simtpu.serve import (
    HTTP_TAXONOMY,
    Overloaded,
    ServeOptions,
    SimtpuServer,
)
from simtpu.serve.batching import Batcher, Query
from simtpu.serve.errors import DeadlineExceeded, error_doc

CONFIG = "examples/simtpu-config.yaml"
OOM_MSG = "RESOURCE_EXHAUSTED: out of memory allocating (injected)"


def _request(port, method, path, body=None, timeout=180):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(
            method, path,
            json.dumps(body) if body is not None else None,
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        doc = json.loads(resp.read())
        return resp.status, doc, dict(resp.getheaders())
    finally:
        conn.close()


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    opts = ServeOptions(
        port=0,
        state_dir=str(tmp_path_factory.mktemp("serve-state")),
        default_deadline_s=180.0,
    )
    srv = SimtpuServer(opts)
    srv.start()
    yield srv
    srv.force_stop()


@pytest.fixture(scope="module")
def sid(server):
    status, doc, _ = _request(
        server.port, "POST", "/v1/sessions", {"config": CONFIG}
    )
    assert status in (200, 201), doc
    return doc["session"]


class TestTaxonomy:
    def test_http_mapping_is_the_documented_table(self):
        # docs/serving.md renders this exact mapping; a drift here must
        # fail loudly, not silently de-sync the docs
        assert HTTP_TAXONOMY == {
            "bad_request": 400,
            "not_found": 404,
            "overloaded": 429,
            "degraded": 503,
            "deadline": 504,
            "audit": 500,
            "internal": 500,
        }

    def test_error_doc_shape(self):
        doc = error_doc(Overloaded("full", retry_after=2.0))
        assert doc["ok"] is False
        assert doc["error"] == "overloaded"
        assert doc["retry_after_s"] == 2.0


class TestLifecycle:
    def test_health_ready_metrics(self, server):
        status, doc, _ = _request(server.port, "GET", "/healthz")
        assert status == 200 and doc["ok"] is True
        status, doc, _ = _request(server.port, "GET", "/readyz")
        assert status == 200 and doc["ready"] is True
        status, doc, _ = _request(server.port, "GET", "/metrics")
        assert status == 200
        assert "serve.requests" in doc["metrics"] or doc["metrics"] == {}

    def test_create_is_idempotent(self, server, sid):
        status, doc, _ = _request(
            server.port, "POST", "/v1/sessions", {"config": CONFIG}
        )
        assert status == 200  # not 201: same problem, same session
        assert doc["session"] == sid
        assert doc["audit_ok"] is True

    def test_unknown_session_404(self, server):
        status, doc, _ = _request(
            server.port, "GET", "/v1/sessions/deadbeef0000"
        )
        assert status == 404 and doc["error"] == "not_found"

    def test_malformed_body_400(self, server, sid):
        # not JSON at all
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=30)
        try:
            conn.request(
                "POST", f"/v1/sessions/{sid}/drain", b"{nope",
                {"Content-Type": "application/json", "Content-Length": "5"},
            )
            resp = conn.getresponse()
            doc = json.loads(resp.read())
        finally:
            conn.close()
        assert resp.status == 400 and doc["error"] == "bad_request"

    def test_bad_config_path_400(self, server):
        status, doc, _ = _request(
            server.port, "POST", "/v1/sessions",
            {"config": "/does/not/exist.yaml"},
        )
        assert status == 400
        assert "ingest failed" in doc["message"]

    def test_unknown_query_kind_404(self, server, sid):
        status, doc, _ = _request(
            server.port, "POST", f"/v1/sessions/{sid}/explode", {}
        )
        assert status == 404

    def test_bad_deadline_type_400(self, server, sid):
        # a malformed deadline is the CLIENT's 400, never a 500 bug
        # report (which would dump a flight bundle per fuzzed request)
        status, doc, _ = _request(
            server.port, "POST", f"/v1/sessions/{sid}/drain",
            {"nodes": [0], "deadline_s": "soon"},
        )
        assert status == 400 and doc["error"] == "bad_request"
        assert "deadline_s" in doc["message"]

    def test_bad_int_fields_400(self, server, sid):
        # client garbage in numeric fields is the taxonomy's 400, never
        # a 500 bug report — and must not poison a coalesced batch
        status, doc, _ = _request(
            server.port, "POST", f"/v1/sessions/{sid}/resilience",
            {"spec": "k=1", "samples": "lots"},
        )
        assert status == 400 and "samples" in doc["message"]
        status, doc, _ = _request(
            server.port, "POST", f"/v1/sessions/{sid}/capacity",
            {"max_new_nodes": "ten"},
        )
        assert status == 400 and "max_new_nodes" in doc["message"]
        # bounds: samples <= 0 would force exhaustive C(n,k) host-side
        status, doc, _ = _request(
            server.port, "POST", f"/v1/sessions/{sid}/resilience",
            {"spec": "k=2", "samples": 0},
        )
        assert status == 400 and "samples" in doc["message"]
        status, doc, _ = _request(
            server.port, "POST", f"/v1/sessions/{sid}/capacity",
            {"max_new_nodes": 10**9},
        )
        assert status == 400 and "max_new_nodes" in doc["message"]

    def test_oversized_body_400_without_reading(self, server, sid):
        conn = http.client.HTTPConnection(
            "127.0.0.1", server.port, timeout=30
        )
        try:
            conn.putrequest("POST", f"/v1/sessions/{sid}/drain")
            conn.putheader("Content-Type", "application/json")
            conn.putheader("Content-Length", str(64 << 20))
            conn.endheaders()
            # no body sent: the daemon must answer WITHOUT reading it
            resp = conn.getresponse()
            doc = json.loads(resp.read())
        finally:
            conn.close()
        assert resp.status == 400
        assert "too large" in doc["message"]

    def test_unknown_drain_node_400(self, server, sid):
        status, doc, _ = _request(
            server.port, "POST", f"/v1/sessions/{sid}/drain",
            {"nodes": ["no-such-node"]},
        )
        assert status == 400 and "unknown node" in doc["message"]


class TestServedAnswers:
    """Served answers are bit-identical to the one-shot oracles."""

    def test_drain_equals_serial_oracle(self, server, sid):
        from simtpu.faults import drain_requeue

        session = server.store.get(sid)
        name = list(session.node_index)[1]
        status, doc, _ = _request(
            server.port, "POST", f"/v1/sessions/{sid}/drain",
            {"nodes": [name]},
        )
        assert status == 200, doc
        mask = np.zeros(len(session.cluster.nodes), bool)
        mask[session.node_index[name]] = True
        with session.lock:
            oracle = drain_requeue(session.pc, mask, restore=True)
        assert doc["evicted"] == len(oracle.evicted_rows)
        assert doc["lost"] == len(oracle.lost_rows)
        assert doc["requeued"] == len(oracle.requeue_rows)
        assert doc["unplaced"] == oracle.unplaced
        assert doc["survived"] == oracle.survived
        pods = session.pc.batch.pods
        oracle_unplaced = sorted(
            (pods[int(r)].get("metadata") or {}).get("name", "")
            for r in oracle.unplaced_rows
        )
        assert sorted(doc["unplaced_pods"]) == oracle_unplaced

    def test_fit_bit_identical_to_one_shot_simulate(self, server, sid):
        from simtpu.api import simulate
        from simtpu.durable.checkpoint import name_seed
        from simtpu.serve.batching import app_from_payload
        from simtpu.workloads.expand import seed_name_hashes

        payload = {
            "workloads": [{
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": "probe", "namespace": "default"},
                "spec": {
                    "replicas": 2,
                    "template": {
                        "metadata": {"labels": {"app": "probe"}},
                        "spec": {"containers": [{
                            "name": "c", "image": "nginx",
                            "resources": {"requests": {
                                "cpu": "1", "memory": "1Gi",
                            }},
                        }]},
                    },
                },
            }],
        }
        status, doc, _ = _request(
            server.port, "POST", f"/v1/sessions/{sid}/fit", dict(payload)
        )
        assert status == 200, doc
        assert doc["fits"] is True
        assert doc["session_unscheduled"] == 0
        assert doc["audit"]["ok"] is True  # every served answer certified
        # replay as a one-shot run with the served seed: the fit places
        # the WHOLE snapshot (cluster + session apps) then the query
        # app, and the query app's placements must match to the pod
        # NAME (the acceptance pin)
        import simtpu.constants as C

        session = server.store.get(sid)
        qname = doc["app"]
        with session.lock:
            seed_name_hashes(name_seed(doc["fingerprint"]))
            result = simulate(
                session.cluster,
                list(session.apps) + [app_from_payload(payload)],
                sched_config=session.sched_config,
            )

        def is_query(pod):
            labels = (pod.get("metadata") or {}).get("labels") or {}
            return labels.get(C.LABEL_APP_NAME) == qname

        oneshot = {}
        for s in result.node_status:
            names = sorted(
                p["metadata"]["name"] for p in s.pods if is_query(p)
            )
            if names:
                oneshot[s.node["metadata"]["name"]] = names
        assert doc["placements"] == oneshot
        assert doc["unscheduled"] == sum(
            1 for u in result.unscheduled_pods if is_query(u.pod)
        )

    def test_resilience_counters_match_direct_sweep(self, server, sid):
        from simtpu.faults import generate_scenarios, sweep_scenarios

        status, doc, _ = _request(
            server.port, "POST", f"/v1/sessions/{sid}/resilience",
            {"spec": "k=1"},
        )
        assert status == 200, doc
        session = server.store.get(sid)
        with session.lock:
            sweep = sweep_scenarios(
                session.pc,
                generate_scenarios(session.cluster.nodes, "k=1"),
            )
        assert doc["scenarios"] == len(sweep.scenarios)
        assert doc["survived"] == int(sweep.survived.sum())
        assert doc["unplaced_max"] == int(sweep.unplaced.max())

    def test_capacity_answers_with_audit(self, server, sid):
        status, doc, _ = _request(
            server.port, "POST", f"/v1/sessions/{sid}/capacity", {}
        )
        assert status == 200, doc
        assert doc["success"] is True
        assert doc["nodes_added"] == 0
        assert doc["audit"]["ok"] is True


class TestCoalescing:
    """K queued sweep queries → one dispatch, bit-identical slices."""

    def test_burst_coalesces_and_matches_serial(self, server, sid, monkeypatch):
        import simtpu.faults.sweep as sweep_mod

        session = server.store.get(sid)
        names = list(session.node_index)
        store = server.store
        batcher = Batcher(store, queue_depth=64)  # worker NOT started
        queries = [
            Query(
                kind="drain", session=session,
                payload={"nodes": [names[i % len(names)]]},
                control=RunControl(),
            )
            for i in range(6)
        ] + [
            Query(
                kind="resilience", session=session,
                payload={"spec": "k=1"}, control=RunControl(),
            )
        ]
        for q in queries:
            batcher.submit(q)
        # count the real engine dispatches under the batch
        real = sweep_mod._fault_sweep
        calls = {"n": 0}

        def counted(*args, **kwargs):
            calls["n"] += 1
            return real(*args, **kwargs)

        monkeypatch.setattr(sweep_mod, "_fault_sweep", counted)
        before = REGISTRY.snapshot()
        batch = batcher._take_batch()
        assert len(batch) == len(queries)  # drains + resilience all fused
        batcher._execute(batch)
        delta = REGISTRY.delta_since(before)
        assert delta["serve.coalesced"] == len(queries) - 1
        assert delta["serve.batches"] == 1
        assert delta["serve.sweeps"] == 1  # ONE sweep for the whole burst
        batched_dispatches = calls["n"]
        for q in queries:
            assert q.error is None, q.error
            assert q.result["batched_queries"] == len(queries)

        # serial floor: one query at a time = one sweep (and at least one
        # engine dispatch) EACH — measurably more than the fused batch
        before = REGISTRY.snapshot()
        calls["n"] = 0
        serial_docs = []
        for q in queries:
            solo = Query(
                kind=q.kind, session=session, payload=q.payload,
                control=RunControl(),
            )
            batcher.submit(solo)
            batcher._execute(batcher._take_batch())
            assert solo.error is None
            serial_docs.append(solo.result)
        delta = REGISTRY.delta_since(before)
        assert delta["serve.sweeps"] == len(queries)
        assert calls["n"] > batched_dispatches

        # bit-identity: every coalesced answer equals its serial twin
        # (batch bookkeeping aside)
        def strip(doc):
            return {
                k: v for k, v in doc.items()
                if k not in ("batched_queries", "batch_scenarios")
            }

        for q, solo_doc in zip(queries, serial_docs):
            assert strip(q.result) == strip(solo_doc)

    def test_http_burst_bumps_coalesce_counter(self, server, sid):
        session = server.store.get(sid)
        names = list(session.node_index)
        before = REGISTRY.value("serve.coalesced")
        results = [None] * 5

        def fire(i):
            results[i] = _request(
                server.port, "POST", f"/v1/sessions/{sid}/drain",
                {"nodes": [names[i % len(names)]]},
            )

        threads = [
            threading.Thread(target=fire, args=(i,)) for i in range(5)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(r[0] == 200 for r in results), [r[:2] for r in results]
        # at least the queries queued behind the first executing batch
        # fused (the exact split depends on arrival timing)
        assert REGISTRY.value("serve.coalesced") > before


class TestRobustnessMatrix:
    def test_deadline_504_while_concurrent_completes(self, server, sid):
        session = server.store.get(sid)
        names = list(session.node_index)
        out = {}

        def slow():
            out["slow"] = _request(
                server.port, "POST", f"/v1/sessions/{sid}/drain",
                {"nodes": [names[0]], "deadline_s": 0.0},
            )

        def ok():
            out["ok"] = _request(
                server.port, "POST", f"/v1/sessions/{sid}/drain",
                {"nodes": [names[1]]},
            )

        threads = [threading.Thread(target=f) for f in (slow, ok)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        status, doc, _ = out["slow"]
        assert status == 504
        assert doc["error"] == "deadline"
        assert "partial" in doc  # structured, even when null
        assert out["ok"][0] == 200  # the daemon and its peers are unharmed

    def test_capacity_deadline_salvages_structured_partial(self, server, sid):
        """The cooperative RunControl path: plan_capacity polls at
        candidate boundaries and hands back the best-so-far partial,
        which rides the 504 body (the CLI exit-3 contract over HTTP)."""
        session = server.store.get(sid)
        control = RunControl(deadline=0.0)
        q = Query(
            kind="capacity", session=session, payload={}, control=control,
        )
        # bypass the queue-expiry fast path: run the query body directly
        # (the fast path is covered by test_deadline_504 above)
        with session.lock:
            server.batcher._run_single(q)
        assert isinstance(q.error, DeadlineExceeded)
        partial = q.error.extra["partial"]
        assert partial["partial"] is True
        assert partial["kind"] == "capacity"

    def test_queue_full_429_in_flight_unharmed(self, server, sid):
        """Fill the admission queue behind a deliberately blocked worker:
        overflow sheds 429 + Retry-After; everything admitted completes
        untouched once the worker unblocks."""
        session = server.store.get(sid)
        name = list(session.node_index)[0]
        small = Batcher(server.store, queue_depth=2)
        small.start()
        with session.lock:  # the worker blocks on the session lock
            admitted = [
                Query(
                    kind="drain", session=session,
                    payload={"nodes": [name]}, control=RunControl(),
                )
                for _ in range(3)
            ]
            small.submit(admitted[0])  # worker picks it up, blocks
            deadline = time.monotonic() + 5
            while small._dq and time.monotonic() < deadline:
                time.sleep(0.01)  # wait for the worker to TAKE #0
            assert not small._dq, "worker never picked up the first query"
            small.submit(admitted[1])
            small.submit(admitted[2])
            shed_before = REGISTRY.value("serve.shed")
            extra = Query(
                kind="drain", session=session,
                payload={"nodes": [name]}, control=RunControl(),
            )
            with pytest.raises(Overloaded) as exc_info:
                small.submit(extra)
            assert exc_info.value.retry_after is not None
            assert REGISTRY.value("serve.shed") == shed_before + 1
        # lock released: the admitted queries all complete correctly
        for q in admitted:
            assert q.done.wait(120), "admitted query never completed"
            assert q.error is None
            assert q.result["ok"] is True
        small.stop(drain=True)

    def test_injected_oom_backoff_correct_answer(self, server, sid, monkeypatch):
        """RESOURCE_EXHAUSTED on the first sweep dispatch: the chunk
        backoff halves and replays; the served answer equals the
        uninjected one and backoff.* counters record the event."""
        import simtpu.faults.sweep as sweep_mod

        status, clean, _ = _request(
            server.port, "POST", f"/v1/sessions/{sid}/resilience",
            {"spec": "k=1"},
        )
        assert status == 200

        real = sweep_mod._fault_sweep
        calls = {"n": 0}

        def fail_first(*args, **kwargs):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError(OOM_MSG)
            return real(*args, **kwargs)

        monkeypatch.setattr(sweep_mod, "_fault_sweep", fail_first)
        before = REGISTRY.value("backoff.events")
        status, doc, _ = _request(
            server.port, "POST", f"/v1/sessions/{sid}/resilience",
            {"spec": "k=1"},
        )
        assert status == 200, doc
        assert REGISTRY.value("backoff.events") > before
        strip = lambda d: {  # noqa: E731 — local comparator
            k: v for k, v in d.items()
            if k not in ("batched_queries", "batch_scenarios")
        }
        assert strip(doc) == strip(clean)

    def test_exhausted_oom_degrades_503_daemon_alive(self, server, sid, monkeypatch):
        """A single-scenario dispatch cannot halve: exhausted backoff
        answers 503 + Retry-After, evicts idle sessions, and the daemon
        keeps serving."""
        import simtpu.faults.sweep as sweep_mod

        def always_oom(*args, **kwargs):
            raise RuntimeError(OOM_MSG)

        monkeypatch.setattr(sweep_mod, "_fault_sweep", always_oom)
        name = list(server.store.get(sid).node_index)[0]
        status, doc, headers = _request(
            server.port, "POST", f"/v1/sessions/{sid}/drain",
            {"nodes": [name]},
        )
        assert status == 503
        assert doc["error"] == "degraded"
        assert "Retry-After" in headers
        monkeypatch.undo()
        # the daemon survived and the session still answers (rehydrated
        # or kept — either way, correct)
        status, doc, _ = _request(
            server.port, "POST", f"/v1/sessions/{sid}/drain",
            {"nodes": [name]},
        )
        assert status == 200 and doc["ok"] is True

    def test_corrupt_checkpoint_rebuilds_fresh(self, server, sid):
        """An unreadable base record must not turn the sid into a
        permanent 500: the store rebuilds fresh (and re-checkpoints),
        exactly as a fresh create would."""
        import glob

        sdir = os.path.join(server.store.state_dir, sid)
        rec = glob.glob(os.path.join(sdir, "rec_base_*.npz"))[0]
        with open(rec, "wb") as f:
            f.write(b"garbage")
        server.store._sessions.pop(sid)
        status, doc, _ = _request(server.port, "GET", f"/v1/sessions/{sid}")
        assert status == 200, doc
        assert doc["session"] == sid

    def test_rehydrate_preserves_extended_resources(self, tmp_path):
        """A session created under --extended-resources must rehydrate
        with the SAME tensorization terms — the recorded lvm/dev/gpu
        vectors carry those widths, and the bit-identity contract covers
        the extended state too."""
        opts = ServeOptions(
            port=0, state_dir=str(tmp_path / "st"),
            extended_resources=("gpu",),
        )
        srv = SimtpuServer(opts)
        srv.start()
        try:
            status, doc, _ = _request(
                srv.port, "POST", "/v1/sessions",
                {"config": "examples/simtpu-gpushare-config.yaml"},
            )
            assert status == 201, doc
            sid2 = doc["session"]
            status, before, _ = _request(
                srv.port, "POST", f"/v1/sessions/{sid2}/drain",
                {"nodes": [0]},
            )
            assert status == 200, before
            # evict the in-memory session; the checkpoint stays
            srv.store._sessions.pop(sid2)
            status, after, _ = _request(
                srv.port, "POST", f"/v1/sessions/{sid2}/drain",
                {"nodes": [0]},
            )
            assert status == 200, after
            assert after == before  # bit-identical through rehydration
            assert srv.store.get(sid2).recovered is True
        finally:
            srv.force_stop()

    def test_sigterm_drains_in_flight_then_stops(self, tmp_path):
        """In-process drain contract: shutdown requested while a query
        is admitted → the query completes, then the server stops."""
        opts = ServeOptions(port=0, state_dir=str(tmp_path / "st"))
        srv = SimtpuServer(opts)
        srv.start()
        try:
            status, doc, _ = _request(
                srv.port, "POST", "/v1/sessions", {"config": CONFIG}
            )
            assert status in (200, 201)
            sid2 = doc["session"]
            session = srv.store.get(sid2)
            name = list(session.node_index)[0]
            out = {}

            def fire():
                out["r"] = _request(
                    srv.port, "POST", f"/v1/sessions/{sid2}/drain",
                    {"nodes": [name]},
                )

            with session.lock:  # hold the worker mid-batch
                t = threading.Thread(target=fire)
                t.start()
                time.sleep(0.2)  # let the query get admitted
                srv.request_shutdown("test-sigterm")
                status, doc, _ = _request(srv.port, "GET", "/readyz")
                assert status == 503 and doc["reason"] == "draining"
            t.join(120)
            assert out["r"][0] == 200  # in-flight work completed
            assert srv.wait(30)  # drain finished cleanly
        finally:
            srv.force_stop()


class TestCrashRecoveryEndToEnd:
    """kill -9 + restart through the real CLI daemon: the session
    rehydrates from its checkpoint and answers bit-identically."""

    def _start(self, state_dir, env):
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "simtpu.cli", "serve",
                "--port", "0", "--state-dir", state_dir,
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
        port = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                time.sleep(0.05)
                continue
            if "listening on http://" in line:
                port = int(line.rsplit(":", 1)[1].split()[0])
                break
        assert port is not None, "daemon never printed its address"
        return proc, port

    def test_kill_9_restart_bit_identical(self, tmp_path):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        state = str(tmp_path / "state")
        proc, port = self._start(state, env)
        try:
            status, doc, _ = _request(
                port, "POST", "/v1/sessions", {"config": CONFIG}
            )
            assert status == 201, doc
            sid = doc["session"]
            status, before, _ = _request(
                port, "POST", f"/v1/sessions/{sid}/drain",
                {"nodes": ["worker-a-0"]},
            )
            assert status == 200
        finally:
            proc.kill()  # SIGKILL: no atexit, no flush — the crash
            proc.wait(30)

        proc, port = self._start(state, env)
        try:
            status, summary, _ = _request(port, "GET", f"/v1/sessions/{sid}")
            assert status == 200
            assert summary["recovered"] is True
            assert summary["placed"] == doc["placed"]
            status, after, _ = _request(
                port, "POST", f"/v1/sessions/{sid}/drain",
                {"nodes": ["worker-a-0"]},
            )
            assert status == 200
            assert after == before  # bit-identical served answer
            # SIGTERM: graceful drain, clean exit 0
            proc.send_signal(signal.SIGTERM)
            assert proc.wait(60) == 0
            rest = proc.stdout.read()
            assert "drained" in rest
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(30)


class TestOffPathZeroCost:
    def test_no_serve_import_on_cli_paths(self):
        """The daemon-off pin (the explain off-path pattern): version and
        a full apply run never import simtpu.serve."""
        code = (
            "import sys\n"
            "from simtpu.cli import main\n"
            "assert main(['version']) == 0\n"
            f"rc = main(['apply', '-f', {CONFIG!r}, '--json'])\n"
            "assert rc == 0, rc\n"
            "assert 'simtpu.serve' not in sys.modules, 'serve imported'\n"
            "assert not any(m.startswith('simtpu.serve') for m in sys.modules)\n"
        )
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=600, env=env,
        )
        assert out.returncode == 0, out.stdout + out.stderr

    def test_parser_registers_serve_without_import(self):
        """Registering the subcommand costs no import; only invoking it
        does (this module imported simtpu.serve itself, so the pin runs
        against the parser's lazy-import structure, not sys.modules)."""
        import inspect

        from simtpu import cli

        src = inspect.getsource(cli._cmd_serve)
        assert "from .serve import" in src  # lazy, inside the function
        src_head = inspect.getsource(cli).split("def _cmd_serve", 1)[0]
        assert "from .serve" not in src_head.replace(
            "lazy", ""
        )  # no module-level serve import
