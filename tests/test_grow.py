"""Append-only vocabulary growth — `extend_state` (ISSUE 20).

The load-bearing pins:

- BIT-IDENTITY MATRIX: a grow-mode engine (dense carry, term axes
  pre-padded to pow2 buckets, carry EXTENDED in place as the vocabulary
  grows — including across a bucket-boundary promotion) places every
  wave bit-identically to tensorize-from-scratch engines in both the
  compact and dense carry layouts, and the final carried planes match.
- NODE GROWTH: `Tensorizer.add_clone_nodes` + `Engine.grow_nodes`
  extends the node axis mid-run bit-identically to a rebuild, and the
  incrementally grown tensorizer is indistinguishable from a
  from-scratch `Tensorizer` over the full node list.
- AUTOSCALE GROWTH: `autoscale.grow_max` lets a replay scale PAST the
  pre-provisioned pool — grown nodes admit a gang the fixed axis
  strands, batched stays pinned to the serial oracle, auditor-clean.
- WARM SERVING: a session's fit queries append into ONE warm engine and
  answer bit-identically to the legacy full-`simulate()` path (pod
  names included — the name-stream fast-forward), with ZERO retensorize
  fallbacks on the common path; the warm capacity fast path completes
  strands on grown template clones and matches the legacy planner.
- COMPILE BUDGET: growth kernels trace once per bucket signature —
  a second same-bucket append adds ZERO `compile.grow`
  (the TestSolveCompileBudget contract, extended to the grow kind).
"""

from __future__ import annotations

import os
import pathlib

import numpy as np
import pytest

from simtpu import constants as C
from simtpu.api import _sort_app_pods
from simtpu.core.objects import AppResource, ResourceTypes, set_label
from simtpu.core.tensorize import Tensorizer
from simtpu.durable.deadline import RunControl
from simtpu.engine.rounds import RoundsEngine
from simtpu.engine.state import ensure_dense
from simtpu.obs.metrics import REGISTRY
from simtpu.parallel.sweep import assemble_planning_problem
from simtpu.synth import make_deployment, make_node, synth_cluster
from simtpu.workloads.expand import (
    get_valid_pods_exclude_daemonset,
    make_valid_pods_by_daemonset,
)

REPO = pathlib.Path(__file__).resolve().parent.parent
CONFIG = str(REPO / "examples" / "simtpu-config.yaml")


def _app(name, deps):
    res = ResourceTypes()
    res.deployments.extend(deps)
    return AppResource(name=name, resource=res)


def make_problem():
    """A small cluster plus four placement waves: a term-rich base, an
    in-bucket vocabulary extension, a pure carry-reuse wave, and a
    many-term wave that promotes the pow2 bucket."""
    cluster = synth_cluster(
        8, seed=11, zones=3, taint_frac=0.1, gpu_frac=0.2, storage_frac=0.3
    )
    waves = [
        _app("w0", [
            make_deployment("a0", 4, 250, 256),
            make_deployment(
                "a1", 4, 250, 256,
                anti_affinity_topo="kubernetes.io/hostname",
                anti_affinity_required=True,
            ),
            make_deployment(
                "a2", 4, 250, 256,
                spread_topo="topology.kubernetes.io/zone", spread_hard=True,
            ),
            make_deployment(
                "a3", 3, 250, 256,
                anti_affinity_topo="kubernetes.io/hostname",
            ),
        ]),
        _app("w1", [
            make_deployment(
                "b0", 3, 250, 256,
                anti_affinity_topo="kubernetes.io/hostname",
                anti_affinity_required=True,
            ),
            make_deployment(
                "b1", 3, 250, 256,
                affinity_topo="topology.kubernetes.io/zone",
            ),
        ]),
        _app("w2", [make_deployment("c0", 4, 250, 256)]),
        _app("w3", [
            make_deployment(
                f"d{i}", 2, 125, 128,
                anti_affinity_topo="kubernetes.io/hostname",
                anti_affinity_required=(i % 2 == 0),
                spread_topo="topology.kubernetes.io/zone",
            )
            for i in range(10)
        ]),
    ]
    return cluster, waves


def expand_app(app, all_nodes):
    pods = get_valid_pods_exclude_daemonset(app.resource)
    for ds in app.resource.daemon_sets:
        pods.extend(make_valid_pods_by_daemonset(ds, all_nodes))
    for pod in pods:
        set_label(pod, C.LABEL_APP_NAME, app.name)
    return _sort_app_pods(pods)


def run_waves(grow: bool, compact=None):
    """Place the four waves incrementally; returns (placements list,
    final dense carried state, engine, tensorizer)."""
    cluster, waves = make_problem()
    tz, all_nodes, _n_base, ordered = assemble_planning_problem(
        cluster, [waves[0]], cluster.nodes[0], 0
    )
    eng = RoundsEngine(tz)
    if grow:
        eng.enable_grow()
    elif compact is not None:
        eng.compact = compact
    placements = []
    batch = tz.add_pods(ordered)
    placements.append(np.asarray(eng.place(batch)[0]))
    for app in waves[1:]:
        batch = tz.add_pods(expand_app(app, all_nodes))
        placements.append(np.asarray(eng.place(batch)[0]))
    state = ensure_dense(eng.carried_state(), tz.freeze())
    return placements, state, eng, tz


def _assert_same_run(a, b):
    pl_a, st_a = a[0], a[1]
    pl_b, st_b = b[0], b[1]
    for i, (x, y) in enumerate(zip(pl_a, pl_b)):
        assert x.shape == y.shape, (i, x.shape, y.shape)
        assert np.array_equal(x, y), (i, np.flatnonzero(x != y))
    for key in type(st_a)._fields:
        x = np.asarray(getattr(st_a, key))
        y = np.asarray(getattr(st_b, key))
        assert x.shape == y.shape, (key, x.shape, y.shape)
        assert np.array_equal(x, y), key


@pytest.fixture(scope="module")
def grow_legs():
    """The grow run (with its counter delta) plus compact and dense
    from-scratch baselines over the same waves."""
    compact_leg = run_waves(False)
    dense_leg = run_waves(False, compact=False)
    before = REGISTRY.snapshot()
    grow_leg = run_waves(True)
    delta = REGISTRY.delta_since(before)
    return {
        "compact": compact_leg, "dense": dense_leg,
        "grow": grow_leg, "delta": delta,
    }


class TestExtendStateBitIdentity:
    @pytest.mark.slow
    def test_matches_compact_from_scratch(self, grow_legs):
        _assert_same_run(grow_legs["grow"], grow_legs["compact"])

    @pytest.mark.slow
    def test_matches_dense_from_scratch(self, grow_legs):
        _assert_same_run(grow_legs["grow"], grow_legs["dense"])

    @pytest.mark.slow
    def test_layout_baselines_agree(self, grow_legs):
        # the matrix closes: compact and dense baselines also agree, so
        # all three layouts answer identically
        _assert_same_run(grow_legs["compact"], grow_legs["dense"])

    @pytest.mark.slow
    def test_extends_fired_not_rebuilds(self, grow_legs):
        d = grow_legs["delta"]
        assert d.get("grow.extends", 0) >= 2, d
        assert d.get("grow.rebuilds", 0) == 0, d

    @pytest.mark.slow
    def test_bucket_promotion_crossed(self, grow_legs):
        # wave 3's ten-deployment burst must actually cross a pow2
        # boundary, or the promotion path went untested
        assert grow_legs["delta"].get("grow.bucket_promotions", 0) >= 1

    def test_grow_rides_compile_count_kinds(self):
        from simtpu.engine.scan import COMPILE_COUNT_KINDS

        assert "grow" in COMPILE_COUNT_KINDS


@pytest.mark.slow
class TestNodeGrowth:
    @pytest.fixture(scope="class")
    def node_legs(self):
        from simtpu.plan.capacity import new_fake_nodes

        def run(grow: bool):
            cluster, waves = make_problem()
            tz, all_nodes, _nb, ordered = assemble_planning_problem(
                cluster, [waves[0]], cluster.nodes[0], 0
            )
            eng = RoundsEngine(tz)
            if grow:
                eng.enable_grow()
            placements = []
            batch = tz.add_pods(ordered)
            placements.append(np.asarray(eng.place(batch)[0]))
            for app in waves[1:3]:
                batch = tz.add_pods(expand_app(app, all_nodes))
                placements.append(np.asarray(eng.place(batch)[0]))
            clones = new_fake_nodes(cluster.nodes[0], 2)
            tz.add_clone_nodes(clones)
            if grow:
                assert eng.grow_nodes(), "grow_nodes should extend the carry"
            batch = tz.add_pods(expand_app(waves[3], all_nodes + clones))
            placements.append(np.asarray(eng.place(batch)[0]))
            state = ensure_dense(eng.carried_state(), tz.freeze())
            return placements, state, tz, all_nodes + clones

        base = run(False)
        before = REGISTRY.snapshot()
        grown = run(True)
        delta = REGISTRY.delta_since(before)
        return base, grown, delta

    def test_mid_run_node_growth_bit_identical(self, node_legs):
        base, grown, delta = node_legs
        _assert_same_run(base, grown)
        assert delta.get("grow.node_extends", 0) == 1, delta
        assert delta.get("grow.rebuilds", 0) == 0, delta

    def test_grown_tensorizer_matches_from_scratch(self, node_legs):
        """The grown tensorizer's frozen planes equal a from-scratch
        Tensorizer over the full node list (domain ids canonicalized —
        interning order may differ, the partition may not)."""
        _base, grown, _delta = node_legs
        _pl, _st, tz, nodes = grown
        cluster, waves = make_problem()
        _tz, _nodes, _nb, ordered = assemble_planning_problem(
            cluster, [waves[0]], cluster.nodes[0], 0
        )
        tz2 = Tensorizer(nodes)
        tz2.add_pods(ordered)
        for w in waves[1:]:
            tz2.add_pods(expand_app(w, nodes))
        a, b = tz.freeze(), tz2.freeze()
        for f in (
            "alloc", "key_kind", "node_dom_small", "term_topo_key",
            "static_mask", "node_pref_score", "taint_intolerable",
            "static_score", "avoid_pen", "s_match", "a_aff_req",
            "a_anti_req", "w_aff_pref", "w_anti_pref", "spread_hard",
            "spread_soft", "ss_host", "ss_zone", "ports", "vol_mask",
            "vol_rw", "vol_ro", "vol_att", "vol_class_mask",
            "attach_limits",
        ):
            x, y = getattr(a, f), getattr(b, f)
            assert x.shape == y.shape, (f, x.shape, y.shape)
            assert np.array_equal(x, y), f
        assert a.node_names == b.node_names
        assert a.resource_names == b.resource_names
        assert a.topo_keys == b.topo_keys

        def canon_dom(node_dom):
            out = np.full_like(node_dom, -1)
            for k in range(node_dom.shape[0]):
                seen = {}
                for i, d in enumerate(node_dom[k]):
                    if d >= 0:
                        out[k, i] = seen.setdefault(int(d), len(seen))
            return out

        assert np.array_equal(canon_dom(a.node_dom), canon_dom(b.node_dom))
        for e in (
            "vg_cap", "vg_req0", "vg_name_id", "has_storage", "sdev_cap",
            "sdev_media", "sdev_alloc0", "gpu_dev_total", "gpu_total",
        ):
            assert np.array_equal(getattr(a.ext, e), getattr(b.ext, e)), e


class TestAutoscaleGrowMax:
    def _doc(self, grow_max: int):
        nodes = [make_node(f"n-{i}", 4000, 16) for i in range(2)]
        return {
            "version": 1, "seed": 3, "horizon_s": 6000.0,
            "cluster": {"nodes": nodes},
            "jobs": [
                # a 12-pod gang the 2-node base can never hold: only
                # grown capacity admits it (immortal — a departure would
                # reset its landing vector and blind the assertion)
                {"name": "surge", "t_s": 10.0,
                 "workload": make_deployment("surge", 12, 1500, 1024)},
            ],
            "autoscale": {
                "interval_s": 120.0, "target_util": 0.6, "pool": 1,
                "node": make_node("tmpl", 4000, 16), "grow_max": grow_max,
            },
        }

    @pytest.mark.slow
    def test_grow_admits_the_stranded_gang_pinned(self):
        from simtpu.timeline import ReplayOptions, replay_trace, trace_from_doc

        doc = self._doc(grow_max=4)
        batched = replay_trace(trace_from_doc(doc), ReplayOptions())
        serial = replay_trace(trace_from_doc(doc), ReplayOptions(serial=True))
        from tests.test_timeline import _assert_pinned

        _assert_pinned(batched, serial)
        assert batched.counts["pool_grow"] >= 1
        assert batched.counts["pool_grow_refused"] == 0
        assert int((batched.nodes >= 0).sum()) == 12, batched.counts
        assert batched.audit["ok"]

    def test_without_grow_max_the_gang_strands(self):
        from simtpu.timeline import ReplayOptions, replay_trace, trace_from_doc

        res = replay_trace(trace_from_doc(self._doc(grow_max=0)),
                           ReplayOptions())
        assert res.counts["pool_grow"] == 0
        assert int((res.nodes >= 0).sum()) < 12


# ---------------------------------------------------------------------------
# warm serving


FIT_PLAIN = {
    "workloads": [{
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "probe", "namespace": "default"},
        "spec": {
            "replicas": 3,
            "template": {
                "metadata": {"labels": {"app": "probe"}},
                "spec": {"containers": [{
                    "name": "c", "image": "nginx",
                    "resources": {"requests": {
                        "cpu": "1", "memory": "1Gi",
                    }},
                }]},
            },
        },
    }],
}
# a vocabulary-growing shape: anti-affinity interns new interpod terms
FIT_ANTI = {
    "workloads": [{
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "probe2", "namespace": "default"},
        "spec": {
            "replicas": 2,
            "template": {
                "metadata": {"labels": {"app": "probe2"}},
                "spec": {
                    "affinity": {"podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [{
                            "topologyKey": "kubernetes.io/hostname",
                            "labelSelector": {
                                "matchLabels": {"app": "probe2"},
                            },
                        }],
                    }},
                    "containers": [{
                        "name": "c", "image": "nginx",
                        "resources": {"requests": {
                            "cpu": "500m", "memory": "512Mi",
                        }},
                    }],
                },
            },
        },
    }],
}


def _store(warm: bool, config=CONFIG, audit=True):
    from simtpu.serve.batching import Batcher
    from simtpu.serve.session import SessionStore

    prev = os.environ.get("SIMTPU_SERVE_WARM")
    os.environ["SIMTPU_SERVE_WARM"] = "1" if warm else "0"
    try:
        store = SessionStore(state_dir="", audit=audit)
        session, created = store.create(config)
    finally:
        if prev is None:
            os.environ.pop("SIMTPU_SERVE_WARM", None)
        else:
            os.environ["SIMTPU_SERVE_WARM"] = prev
    assert created and session.warm is warm
    return Batcher(store), session


def _fit(batcher, session, payload):
    from simtpu.serve.batching import Query

    q = Query(kind="fit", session=session, payload=payload,
              control=RunControl())
    with session.lock:
        return batcher._run_fit(q)


def _capacity(batcher, session, payload):
    from simtpu.serve.batching import Query

    q = Query(kind="capacity", session=session, payload=payload,
              control=RunControl())
    with session.lock:
        return batcher._run_capacity(q)


def _drain(batcher, session):
    from simtpu.serve.batching import Query

    q = Query(kind="drain", session=session,
              payload={"nodes": [list(session.node_index)[1]]},
              control=RunControl())
    with session.lock:
        batcher._run_sweep_batch(session, [q])
    assert q.error is None, q.error
    return {k: v for k, v in q.result.items()
            if k not in ("batched_queries", "batch_scenarios")}


@pytest.fixture(scope="module")
def warm_session():
    return _store(warm=True)


class TestWarmServe:
    FIT_KEYS = ("fits", "unscheduled", "session_unscheduled", "placements",
                "app", "preempted")

    @pytest.mark.slow
    def test_warm_fit_bit_identical_to_legacy(self, warm_session):
        """The acceptance pin: the warm append answer equals the legacy
        full-simulate() answer — placements to the POD NAME (the
        name-stream fast-forward covers the session base's draws)."""
        batcher, session = warm_session
        doc_w = _fit(batcher, session, FIT_PLAIN)
        assert doc_w["warm"] is True, doc_w
        assert doc_w["audit"]["ok"] is True
        b2, s2 = _store(warm=False)
        doc_c = _fit(b2, s2, FIT_PLAIN)
        assert "warm" not in doc_c
        assert s2.fingerprint == session.fingerprint
        for k in self.FIT_KEYS:
            assert doc_w[k] == doc_c[k], (k, doc_w[k], doc_c[k])

    def test_repeat_query_stays_on_the_carry(self, warm_session):
        batcher, session = warm_session
        doc1 = _fit(batcher, session, FIT_PLAIN)
        before = REGISTRY.snapshot()
        doc2 = _fit(batcher, session, FIT_PLAIN)
        delta = REGISTRY.delta_since(before)
        assert doc2["placements"] == doc1["placements"]
        assert delta.get("grow.retensorize_fallbacks", 0) == 0, delta
        assert delta.get("grow.rebuilds", 0) == 0, delta

    @pytest.mark.slow
    def test_vocab_growing_query_extends_in_place(self, warm_session):
        batcher, session = warm_session
        before = REGISTRY.snapshot()
        doc = _fit(batcher, session, FIT_ANTI)
        delta = REGISTRY.delta_since(before)
        assert doc["fits"], doc
        assert delta.get("grow.extends", 0) >= 1, delta
        assert delta.get("grow.rebuilds", 0) == 0, delta
        assert delta.get("grow.retensorize_fallbacks", 0) == 0, delta

    @pytest.mark.slow
    def test_drain_stable_across_fit_queries(self, warm_session):
        batcher, session = warm_session
        d0 = _drain(batcher, session)
        _fit(batcher, session, FIT_PLAIN)
        assert _drain(batcher, session) == d0

    def test_priority_payload_takes_the_counted_fallback(self, warm_session):
        """A genuine vocabulary-class miss: query pods carrying
        priorities need the legacy path's preemption semantics."""
        batcher, session = warm_session
        payload = {"workloads": [dict(FIT_PLAIN["workloads"][0])]}
        payload["workloads"][0] = {
            **payload["workloads"][0],
            "spec": {
                **payload["workloads"][0]["spec"],
                "template": {
                    "metadata": {"labels": {"app": "probe"}},
                    "spec": {
                        "priority": 100,
                        "containers": [{
                            "name": "c", "image": "nginx",
                            "resources": {"requests": {
                                "cpu": "1", "memory": "1Gi",
                            }},
                        }],
                    },
                },
            },
        }
        before = REGISTRY.snapshot()
        doc = _fit(batcher, session, payload)
        delta = REGISTRY.delta_since(before)
        assert doc["fits"] is not None
        assert delta.get("grow.retensorize_fallbacks", 0) == 1, delta

    def test_grow_block_in_every_response(self, warm_session):
        batcher, session = warm_session
        doc = _fit(batcher, session, FIT_PLAIN)
        g = doc["engine"]["grow"]
        for k in ("extends", "bucket_promotions", "node_extends",
                  "rebuilds", "retensorize_fallbacks", "compile.grow"):
            assert isinstance(g[k], int), (k, g)
        assert g["warm"] is True
        assert g["buckets"]["t_cap"] >= g["buckets"]["terms"]

    def test_warm_capacity_fully_placed_session(self, warm_session):
        batcher, session = warm_session
        doc = _capacity(batcher, session, {})
        assert doc["warm"] is True, doc
        assert doc["success"] and doc["nodes_added"] == 0, doc
        assert doc["audit"]["ok"] is True


NODE_TMPL = """\
apiVersion: v1
kind: Node
metadata:
  name: worker-template
  labels:
    kubernetes.io/hostname: worker-template
    topology.kubernetes.io/zone: zone-a
status:
  allocatable:
    cpu: "16"
    memory: 32Gi
    pods: "110"
  capacity:
    cpu: "16"
    memory: 32Gi
    pods: "110"
"""


@pytest.fixture(scope="module")
def strands_config(tmp_path_factory):
    """A Config CR whose base (two 4-cpu nodes + a DaemonSet) strands
    six of the heavy app's eight 3-cpu replicas — capacity planning must
    grow template clones."""
    root = tmp_path_factory.mktemp("strands")
    cl = root / "cluster"
    ap = root / "app"
    cl.mkdir()
    ap.mkdir()
    nodes = []
    for i, zone in enumerate(("zone-a", "zone-b")):
        nodes.append(
            "apiVersion: v1\nkind: Node\nmetadata:\n"
            f"  name: small-{i}\n  labels:\n"
            f"    kubernetes.io/hostname: small-{i}\n"
            f"    topology.kubernetes.io/zone: {zone}\n"
            "status:\n  allocatable:\n    cpu: \"4\"\n    memory: 8Gi\n"
            "    pods: \"110\"\n  capacity:\n    cpu: \"4\"\n"
            "    memory: 8Gi\n    pods: \"110\"\n"
        )
    (cl / "nodes.yaml").write_text("---\n".join(nodes))
    (cl / "workloads.yaml").write_text(
        "apiVersion: apps/v1\nkind: DaemonSet\nmetadata:\n  name: agent\n"
        "  namespace: kube-system\nspec:\n  selector:\n    matchLabels:\n"
        "      app: agent\n  template:\n    metadata:\n      labels:\n"
        "        app: agent\n    spec:\n      containers:\n"
        "        - name: agent\n          image: registry.example.com/a:1\n"
        "          resources:\n            requests:\n"
        "              cpu: 200m\n              memory: 128Mi\n"
    )
    (ap / "app.yaml").write_text(
        "apiVersion: apps/v1\nkind: Deployment\nmetadata:\n  name: heavy\n"
        "  namespace: default\nspec:\n  replicas: 8\n  selector:\n"
        "    matchLabels:\n      app: heavy\n  template:\n    metadata:\n"
        "      labels:\n        app: heavy\n    spec:\n      containers:\n"
        "        - name: c\n          image: registry.example.com/h:1\n"
        "          resources:\n            requests:\n"
        "              cpu: \"3\"\n              memory: 2Gi\n"
    )
    (root / "worker.yaml").write_text(NODE_TMPL)
    cfg = root / "config.yaml"
    cfg.write_text(
        "apiVersion: simon/v1alpha1\nkind: Config\nmetadata:\n"
        "  name: strands\nspec:\n  cluster:\n"
        f"    customConfig: {cl}\n  appList:\n"
        f"    - name: heavy\n      path: {ap}\n"
        f"  newNode: {root / 'worker.yaml'}\n"
    )
    return str(cfg)


class TestWarmCapacityStrands:
    @pytest.fixture(scope="class")
    def stranded(self, strands_config):
        batcher, session = _store(warm=True, config=strands_config)
        assert int(np.sum(np.asarray(session.pc.nodes) < 0)) > 0
        return batcher, session

    @pytest.mark.slow
    def test_completes_strands_and_matches_legacy(self, stranded,
                                                  strands_config):
        batcher, session = stranded
        before = REGISTRY.snapshot()
        doc = _capacity(batcher, session, {"max_new_nodes": 8})
        delta = REGISTRY.delta_since(before)
        assert doc["warm"] is True, doc
        assert doc["success"] and doc["nodes_added"] >= 1, doc
        assert doc["audit"]["ok"] is True, doc.get("audit")
        assert delta.get("grow.retensorize_fallbacks", 0) == 0, delta
        b2, s2 = _store(warm=False, config=strands_config)
        doc_c = _capacity(b2, s2, {"max_new_nodes": 8})
        assert doc_c["success"] == doc["success"]
        assert doc_c["nodes_added"] == doc["nodes_added"]

    @pytest.mark.slow
    def test_overlay_cached_and_session_isolated(self, stranded):
        batcher, session = stranded
        doc = _capacity(batcher, session, {"max_new_nodes": 8})
        before = REGISTRY.snapshot()
        doc2 = _capacity(batcher, session, {"max_new_nodes": 8})
        delta = REGISTRY.delta_since(before)
        assert doc2["nodes_added"] == doc["nodes_added"]
        assert delta.get("grow.node_extends", 0) == 0, delta
        # the hypothetical clones never leak into the session base
        tiny = {"workloads": [{
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "tiny", "namespace": "default"},
            "spec": {"replicas": 1, "template": {
                "metadata": {"labels": {"app": "tiny"}},
                "spec": {"containers": [{
                    "name": "c", "image": "nginx",
                    "resources": {"requests": {
                        "cpu": "100m", "memory": "64Mi",
                    }},
                }]},
            }},
        }]}
        docf = _fit(batcher, session, tiny)
        assert docf["warm"] is True and docf["fits"], docf
        assert set(docf["placements"]) <= set(session.node_index)


class TestGrowCompileBudget:
    """Growth kernels trace once per (old bucket, new bucket,
    appended-row bucket) signature — the trace-once-per-bucket contract
    TestSolveCompileBudget pins for the solve kind."""

    def test_same_bucket_appends_trace_nothing_new(self):
        def small(i):
            return _app(f"q{i}", [
                make_deployment(
                    f"q{i}a", 3, 250, 256,
                    anti_affinity_topo="kubernetes.io/hostname",
                    anti_affinity_required=True,
                ),
                make_deployment(
                    f"q{i}b", 3, 250, 256,
                    affinity_topo="topology.kubernetes.io/zone",
                ),
            ])

        cluster, waves = make_problem()
        tz, all_nodes, _nb, ordered = assemble_planning_problem(
            cluster, [waves[0]], cluster.nodes[0], 0
        )
        eng = RoundsEngine(tz)
        eng.enable_grow()
        eng.place(tz.add_pods(ordered))
        # the many-term wave promotes the bucket, anchoring the term
        # axes at the BOTTOM of a fresh pow2 cap — the appends below
        # cannot cross a boundary and the test measures pure reuse
        eng.place(tz.add_pods(expand_app(waves[3], all_nodes)))
        # first small append may trace its extend signature once...
        eng.place(tz.add_pods(expand_app(small(0), all_nodes)))
        caps = (eng._grow_ref["t_cap"], eng._grow_ref["ti_cap"])
        before = REGISTRY.snapshot()
        # ...the SECOND append with the same bucket signature (same app
        # shape, fresh names → new groups + terms inside the same pow2
        # bucket) must trace NOTHING
        eng.place(tz.add_pods(expand_app(small(1), all_nodes)))
        delta = REGISTRY.delta_since(before)
        assert (eng._grow_ref["t_cap"], eng._grow_ref["ti_cap"]) == caps
        assert delta.get("grow.bucket_promotions", 0) == 0, delta
        assert delta.get("compile.grow", 0) == 0, delta
        assert delta.get("grow.rebuilds", 0) == 0, delta
        assert delta.get("grow.extends", 0) >= 1, delta
