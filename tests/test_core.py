"""Unit tests: quantity grammar, YAML ingestion, predicate matching."""

import os

import pytest

from simtpu.core.match import (
    node_should_run_pod,
    pod_matches_node_selector_and_affinity,
    pod_tolerates_node_taints,
    toleration_tolerates_taint,
)
from simtpu.core.objects import pod_requests
from simtpu.core.quantity import format_quantity, parse_quantity
from simtpu.io.cluster import create_cluster_resource_from_cluster_config
from simtpu.io.yaml_loader import load_resources


class TestQuantity:
    @pytest.mark.parametrize(
        "text,expected",
        [
            ("100m", 0.1),
            ("1500m", 1.5),
            ("8", 8.0),
            ("16Gi", 16 * 2**30),
            ("512Mi", 512 * 2**20),
            ("32560Mi", 32560 * 2**20),
            ("1", 1.0),
            ("0", 0.0),
            ("107374182400", 107374182400.0),
            ("2k", 2000.0),
            ("1e3", 1000.0),
            (110, 110.0),
            (None, 0.0),
        ],
    )
    def test_parse(self, text, expected):
        assert parse_quantity(text) == expected

    def test_bad(self):
        with pytest.raises(ValueError):
            parse_quantity("banana")

    def test_format_roundtrip(self):
        assert format_quantity(1.5, "cpu") == "1500m"
        assert format_quantity(16 * 2**30, "mem") == "16Gi"


class TestIngestion:
    def test_demo1_cluster(self, example_dir):
        res = create_cluster_resource_from_cluster_config(
            os.path.join(example_dir, "cluster/demo_1")
        )
        names = sorted(n["metadata"]["name"] for n in res.nodes)
        assert names == ["master-1", "master-2", "master-3", "worker-1"]
        # static pods from manifests/ + kube-proxy daemonsets + coredns + metrics-server
        assert len(res.pods) >= 3
        assert len(res.daemon_sets) == 3
        assert len(res.deployments) == 1
        assert len(res.storage_classes) == 3
        # node-1.json storage annotations attached by name match
        anno = {n["metadata"]["name"]: n["metadata"].get("annotations", {}) for n in res.nodes}
        assert "simon/node-local-storage" in anno["master-1"]
        assert "simon/node-local-storage" in anno["worker-1"]
        assert "simon/node-local-storage" not in anno["master-2"]

    def test_simple_app(self, example_dir):
        res = load_resources(os.path.join(example_dir, "application/simple"))
        assert len(res.deployments) == 1
        assert len(res.daemon_sets) == 1
        assert len(res.jobs) == 1
        assert len(res.pods) == 1
        assert len(res.stateful_sets) == 1
        assert len(res.replica_sets) == 1

    def test_gpushare_cluster(self, example_dir):
        res = load_resources(os.path.join(example_dir, "cluster/gpushare"))
        assert len(res.nodes) == 2
        alloc = res.nodes[0]["status"]["allocatable"]
        assert parse_quantity(alloc["alibabacloud.com/gpu-count"]) == 2


class TestPodRequests:
    def test_sum_and_init_max(self):
        pod = {
            "spec": {
                "containers": [
                    {"name": "a", "resources": {"requests": {"cpu": "500m", "memory": "1Gi"}}},
                    {"name": "b", "resources": {"requests": {"cpu": "250m"}}},
                ],
                "initContainers": [
                    {"name": "init", "resources": {"requests": {"cpu": "2", "memory": "64Mi"}}}
                ],
            }
        }
        req = pod_requests(pod)
        assert req["cpu"] == 2.0  # init container dominates
        assert req["memory"] == 2**30

    def test_limits_default_requests(self):
        pod = {"spec": {"containers": [{"name": "a", "resources": {"limits": {"cpu": "1"}}}]}}
        assert pod_requests(pod)["cpu"] == 1.0


MASTER_TAINT = {"key": "node-role.kubernetes.io/master", "effect": "NoSchedule"}


def _node(name, labels=None, taints=None):
    n = {
        "kind": "Node",
        "metadata": {"name": name, "labels": labels or {}},
        "spec": {},
        "status": {"allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}},
    }
    if taints:
        n["spec"]["taints"] = taints
    return n


class TestMatch:
    def test_toleration_exists_all(self):
        assert toleration_tolerates_taint({"operator": "Exists"}, MASTER_TAINT)

    def test_toleration_effect_mismatch(self):
        tol = {"key": "node-role.kubernetes.io/master", "effect": "NoExecute"}
        assert not toleration_tolerates_taint(tol, MASTER_TAINT)

    def test_taint_filter(self):
        master = _node("m", {"node-role.kubernetes.io/master": ""}, [MASTER_TAINT])
        pod = {"metadata": {"name": "p"}, "spec": {}}
        assert not pod_tolerates_node_taints(pod, master)
        pod["spec"]["tolerations"] = [
            {"key": "node-role.kubernetes.io/master", "operator": "Exists", "effect": "NoSchedule"}
        ]
        assert pod_tolerates_node_taints(pod, master)

    def test_node_selector(self):
        worker = _node("w", {"node-role.kubernetes.io/worker": ""})
        pod = {
            "metadata": {"name": "p"},
            "spec": {"nodeSelector": {"node-role.kubernetes.io/master": ""}},
        }
        assert not pod_matches_node_selector_and_affinity(pod, worker)
        master = _node("m", {"node-role.kubernetes.io/master": ""})
        assert pod_matches_node_selector_and_affinity(pod, master)

    def test_affinity_exists_and_doesnotexist(self):
        master = _node("m", {"node-role.kubernetes.io/master": ""})
        worker = _node("w", {"node-role.kubernetes.io/worker": ""})
        def req(op): return {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [
                    {
                        "matchExpressions": [
                            {"key": "node-role.kubernetes.io/master", "operator": op}
                        ]
                    }
                ]
            }
        }
        pod = {"metadata": {"name": "p"}, "spec": {"affinity": {"nodeAffinity": req("Exists")}}}
        assert pod_matches_node_selector_and_affinity(pod, master)
        assert not pod_matches_node_selector_and_affinity(pod, worker)
        pod["spec"]["affinity"]["nodeAffinity"] = req("DoesNotExist")
        assert not pod_matches_node_selector_and_affinity(pod, master)
        assert pod_matches_node_selector_and_affinity(pod, worker)

    def test_not_in_matches_absent_key(self):
        # apimachinery selector.go:207-211 — NotIn matches when key is absent
        from simtpu.core.match import match_requirement

        req = {"key": "role", "operator": "NotIn", "values": ["master"]}
        assert match_requirement({}, req)
        assert not match_requirement({"role": "master"}, req)
        assert match_requirement({"role": "worker"}, req)

    def test_match_fields_pinning(self):
        n1, n2 = _node("n1"), _node("n2")
        pod = {
            "metadata": {"name": "p"},
            "spec": {
                "affinity": {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {
                                    "matchFields": [
                                        {
                                            "key": "metadata.name",
                                            "operator": "In",
                                            "values": ["n1"],
                                        }
                                    ]
                                }
                            ]
                        }
                    }
                }
            },
        }
        assert node_should_run_pod(n1, pod)
        assert not node_should_run_pod(n2, pod)
