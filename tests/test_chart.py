"""Helm chart renderer tests (`simtpu/chart.py` vs `pkg/chart/chart.go`)."""

import yaml

import pytest

from simtpu.chart import ChartRenderError, process_chart, render_template

YODA = "/root/reference/example/application/charts/yoda"


class TestTemplateEngine:
    def test_field_access_and_root(self):
        ctx = {"Values": {"a": {"b": "x"}}, "Release": {"Name": "r"}}
        assert render_template("{{ .Values.a.b }}/{{ $.Release.Name }}", ctx) == "x/r"

    def test_if_else(self):
        ctx = {"Values": {"on": True, "off": False}}
        tpl = "{{- if .Values.off }}A{{- else if .Values.on }}B{{- else }}C{{- end }}"
        assert render_template(tpl, ctx) == "B"

    def test_trim_markers(self):
        out = render_template("a\n  {{- if true }}\nb\n{{- end }}\nc", {})
        assert out == "a\nb\nc"

    def test_int_and_pipeline(self):
        ctx = {"Values": {"port": "32747"}}
        assert render_template("{{ int .Values.port }}", ctx) == "32747"
        assert render_template("{{ .Values.port | int }}", ctx) == "32747"

    def test_quote_default(self):
        assert render_template('{{ "x" | quote }}', {}) == '"x"'
        assert render_template('{{ .Values.missing | default "d" }}', {"Values": {}}) == "d"

    def test_unsupported_construct_raises(self):
        with pytest.raises(ChartRenderError):
            render_template("{{ range .Values.x }}{{ end }}", {}, where="t.yaml")

    def test_missing_value_formats_like_go(self):
        assert render_template("{{ .Values.nope }}", {"Values": {}}) == "<no value>"


class TestProcessChart:
    def test_yoda_renders_install_ordered(self, example_dir):
        docs = [yaml.safe_load(d) for d in process_chart("yoda", YODA)]
        kinds = [d["kind"] for d in docs]
        assert len(docs) == 14
        # InstallOrder: all StorageClasses before Service before workloads
        assert kinds[:5] == ["StorageClass"] * 5
        assert kinds.index("Service") < kinds.index("DaemonSet")
        assert kinds[-2:] == ["Job", "CronJob"]

    def test_yoda_values_flow_through(self, example_dir):
        docs = [yaml.safe_load(d) for d in process_chart("yoda", YODA)]
        scs = [d for d in docs if d["kind"] == "StorageClass"]
        names = {d["metadata"]["name"] for d in scs}
        assert "yoda-lvm-default" in names
        cron = next(d for d in docs if d["kind"] == "CronJob")
        assert cron["spec"]["schedule"] == "0 * * * *"

    def test_release_name_is_app_name(self, example_dir):
        # chart.go:24 overrides the chart name with the configured app name
        docs_a = process_chart("alpha", YODA)
        docs_b = process_chart("yoda", YODA)
        assert len(docs_a) == len(docs_b)
