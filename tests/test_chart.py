"""Helm chart renderer tests (`simtpu/chart.py` vs `pkg/chart/chart.go`)."""

import yaml

import pytest

from simtpu.chart import ChartRenderError, process_chart, render_template

YODA = "/root/reference/example/application/charts/yoda"


class TestTemplateEngine:
    def test_field_access_and_root(self):
        ctx = {"Values": {"a": {"b": "x"}}, "Release": {"Name": "r"}}
        assert render_template("{{ .Values.a.b }}/{{ $.Release.Name }}", ctx) == "x/r"

    def test_if_else(self):
        ctx = {"Values": {"on": True, "off": False}}
        tpl = "{{- if .Values.off }}A{{- else if .Values.on }}B{{- else }}C{{- end }}"
        assert render_template(tpl, ctx) == "B"

    def test_trim_markers(self):
        out = render_template("a\n  {{- if true }}\nb\n{{- end }}\nc", {})
        assert out == "a\nb\nc"

    def test_int_and_pipeline(self):
        ctx = {"Values": {"port": "32747"}}
        assert render_template("{{ int .Values.port }}", ctx) == "32747"
        assert render_template("{{ .Values.port | int }}", ctx) == "32747"

    def test_quote_default(self):
        assert render_template('{{ "x" | quote }}', {}) == '"x"'
        assert render_template('{{ .Values.missing | default "d" }}', {"Values": {}}) == "d"

    def test_unsupported_construct_raises(self):
        # loud failure outside the subset: unknown functions never render
        with pytest.raises(ChartRenderError):
            render_template("{{ derivePassword .Values.x }}", {}, where="t.yaml")
        with pytest.raises(ChartRenderError):
            render_template("{{ if .x }}no end", {}, where="t.yaml")

    def test_missing_value_formats_like_go(self):
        assert render_template("{{ .Values.nope }}", {"Values": {}}) == "<no value>"


class TestProcessChart:
    def test_yoda_renders_install_ordered(self, example_dir):
        docs = [yaml.safe_load(d) for d in process_chart("yoda", YODA)]
        kinds = [d["kind"] for d in docs]
        assert len(docs) == 14
        # InstallOrder: all StorageClasses before Service before workloads
        assert kinds[:5] == ["StorageClass"] * 5
        assert kinds.index("Service") < kinds.index("DaemonSet")
        assert kinds[-2:] == ["Job", "CronJob"]

    def test_yoda_values_flow_through(self, example_dir):
        docs = [yaml.safe_load(d) for d in process_chart("yoda", YODA)]
        scs = [d for d in docs if d["kind"] == "StorageClass"]
        names = {d["metadata"]["name"] for d in scs}
        assert "yoda-lvm-default" in names
        cron = next(d for d in docs if d["kind"] == "CronJob")
        assert cron["spec"]["schedule"] == "0 * * * *"

    def test_release_name_is_app_name(self, example_dir):
        # chart.go:24 overrides the chart name with the configured app name
        docs_a = process_chart("alpha", YODA)
        docs_b = process_chart("yoda", YODA)
        assert len(docs_a) == len(docs_b)


class TestControlStructures:
    """range / with / variables / define-include-template / parens — the
    full-engine semantics VERDICT r1 task 6 asked for (`pkg/chart/chart.go`
    links the real Helm v3 engine; this is the offline subset grown to it)."""

    def test_range_list(self):
        tpl = "{{ range .Values.items }}[{{ . }}]{{ end }}"
        assert render_template(tpl, {"Values": {"items": ["a", "b"]}}) == "[a][b]"

    def test_range_with_index_and_value_vars(self):
        tpl = "{{ range $i, $v := .Values.items }}{{ $i }}={{ $v }};{{ end }}"
        assert render_template(tpl, {"Values": {"items": ["x", "y"]}}) == "0=x;1=y;"

    def test_range_single_var_binds_value(self):
        tpl = "{{ range $v := .Values.items }}{{ $v }}{{ end }}"
        assert render_template(tpl, {"Values": {"items": [1, 2, 3]}}) == "123"

    def test_range_map_sorted_keys(self):
        tpl = "{{ range $k, $v := .Values.m }}{{ $k }}:{{ $v }} {{ end }}"
        out = render_template(tpl, {"Values": {"m": {"b": 2, "a": 1}}})
        assert out == "a:1 b:2 "

    def test_range_else_on_empty(self):
        tpl = "{{ range .Values.items }}x{{ else }}none{{ end }}"
        assert render_template(tpl, {"Values": {"items": []}}) == "none"

    def test_range_dollar_is_root(self):
        tpl = "{{ range .Values.items }}{{ $.Release.Name }}-{{ . }} {{ end }}"
        ctx = {"Values": {"items": ["a"]}, "Release": {"Name": "rel"}}
        assert render_template(tpl, ctx) == "rel-a "

    def test_with_rebinds_dot(self):
        tpl = "{{ with .Values.img }}{{ .repo }}:{{ .tag }}{{ end }}"
        ctx = {"Values": {"img": {"repo": "r", "tag": "t"}}}
        assert render_template(tpl, ctx) == "r:t"

    def test_with_else_on_falsy(self):
        tpl = "{{ with .Values.none }}x{{ else }}fallback{{ end }}"
        assert render_template(tpl, {"Values": {}}) == "fallback"

    def test_variables_declare_assign_scope(self):
        tpl = (
            "{{ $x := 1 }}{{ $x }}"
            "{{ if true }}{{ $x = 2 }}{{ end }}{{ $x }}"
        )
        assert render_template(tpl, {}) == "12"

    def test_parenthesized_pipeline(self):
        tpl = '{{ if and (eq .Values.a "x") (not .Values.b) }}yes{{ end }}'
        assert render_template(tpl, {"Values": {"a": "x", "b": False}}) == "yes"

    def test_define_include_nindent(self):
        tpl = (
            '{{- define "labels" }}app: {{ .Chart.Name }}{{ end -}}'
            'labels:{{ include "labels" . | nindent 2 }}'
        )
        out = render_template(tpl, {"Chart": {"Name": "c"}})
        assert out == "labels:\n  app: c"

    def test_template_statement(self):
        tpl = '{{ define "t" }}[{{ . }}]{{ end }}{{ template "t" .Values.x }}'
        assert render_template(tpl, {"Values": {"x": "v"}}) == "[v]"

    def test_sprig_functions(self):
        assert render_template('{{ "hello-world" | trunc 5 }}', {}) == "hello"
        assert render_template('{{ printf "%s-%d" "a" 3 }}', {}) == "a-3"
        assert render_template('{{ add 1 2 3 }}', {}) == "6"
        assert render_template('{{ ternary "y" "n" true }}', {}) == "y"
        assert (
            render_template('{{ list "a" "b" | join "," }}', {}) == "a,b"
        )
        assert render_template('{{ trimSuffix "-x" "name-x" }}', {}) == "name"

    def test_required_raises_on_missing(self):
        with pytest.raises(ChartRenderError):
            render_template(
                '{{ required "a.b is required" .Values.a }}', {"Values": {}}
            )

    def test_tpl_renders_string(self):
        tpl = '{{ tpl .Values.t . }}'
        ctx = {"Values": {"t": "{{ .Release.Name }}"}, "Release": {"Name": "r"}}
        assert render_template(tpl, ctx) == "r"


class TestHelperChart:
    """A chart exercising `_helpers.tpl` includes + a range loop end-to-end
    (the VERDICT r1 task 6 'done' bar)."""

    def _write_chart(self, root):
        (root / "Chart.yaml").write_text(
            "apiVersion: v2\nname: helper-demo\nversion: 0.1.0\n"
        )
        (root / "values.yaml").write_text(
            "replicas: 2\nports: [8080, 9090]\nlabels:\n  tier: web\n"
        )
        tdir = root / "templates"
        tdir.mkdir()
        (tdir / "_helpers.tpl").write_text(
            '{{- define "demo.fullname" -}}\n'
            '{{- printf "%s-%s" .Release.Name .Chart.Name | trunc 63 | trimSuffix "-" -}}\n'
            "{{- end -}}\n"
            '{{- define "demo.labels" -}}\n'
            "app: {{ .Chart.Name }}\n"
            "release: {{ .Release.Name }}\n"
            "{{- range $k, $v := .Values.labels }}\n"
            "{{ $k }}: {{ $v }}\n"
            "{{- end }}\n"
            "{{- end -}}\n"
        )
        (tdir / "deployment.yaml").write_text(
            "apiVersion: apps/v1\n"
            "kind: Deployment\n"
            "metadata:\n"
            '  name: {{ include "demo.fullname" . }}\n'
            "  labels:\n"
            '    {{- include "demo.labels" . | nindent 4 }}\n'
            "spec:\n"
            "  replicas: {{ .Values.replicas }}\n"
            "  template:\n"
            "    spec:\n"
            "      containers:\n"
            "        - name: app\n"
            "          ports:\n"
            "            {{- range .Values.ports }}\n"
            "            - containerPort: {{ . }}\n"
            "            {{- end }}\n"
        )
        (tdir / "service.yaml").write_text(
            "apiVersion: v1\n"
            "kind: Service\n"
            "metadata:\n"
            '  name: {{ include "demo.fullname" . }}\n'
            "spec:\n"
            "  ports:\n"
            "    {{- range $i, $p := .Values.ports }}\n"
            "    - name: port-{{ $i }}\n"
            "      port: {{ $p }}\n"
            "    {{- end }}\n"
        )

    def test_renders_with_helpers_and_range(self, tmp_path):
        self._write_chart(tmp_path)
        docs = [yaml.safe_load(d) for d in process_chart("myapp", str(tmp_path))]
        assert [d["kind"] for d in docs] == ["Service", "Deployment"]  # InstallOrder
        svc, dep = docs
        # chart.go:24 overrides the chart name with the app name, so
        # .Chart.Name == .Release.Name == "myapp"
        assert dep["metadata"]["name"] == "myapp-myapp"
        assert dep["metadata"]["labels"] == {
            "app": "myapp",
            "release": "myapp",
            "tier": "web",
        }
        assert dep["spec"]["replicas"] == 2
        ports = dep["spec"]["template"]["spec"]["containers"][0]["ports"]
        assert [p["containerPort"] for p in ports] == [8080, 9090]
        assert [p["port"] for p in svc["spec"]["ports"]] == [8080, 9090]
        assert [p["name"] for p in svc["spec"]["ports"]] == ["port-0", "port-1"]

    def test_block_renders_with_argument(self):
        tpl = '{{ block "b" .Values.img }}{{ .repo }}{{ end }}'
        out = render_template(tpl, {"Values": {"img": {"repo": "r"}}})
        assert out == "r"

    def test_duplicate_else_rejected(self):
        with pytest.raises(ChartRenderError):
            render_template(
                "{{ range .Values.x }}a{{ else }}b{{ else }}c{{ end }}",
                {"Values": {"x": []}},
            )
        with pytest.raises(ChartRenderError):
            render_template("{{ if .x }}a{{ else }}b{{ else }}c{{ end }}", {})

    def test_trim_suffix_empty_is_identity(self):
        assert render_template('{{ trimSuffix "" "abc" }}', {}) == "abc"

    def test_merge_is_deep(self):
        ctx = {
            "Values": {
                "common": {"labels": {"a": "1"}, "x": "keep"},
                "overrides": {"labels": {"b": "2"}, "x": "lose", "y": "new"},
            }
        }
        out = render_template(
            "{{ merge .Values.common .Values.overrides | toJson }}", ctx
        )
        import json as _json

        assert _json.loads(out) == {
            "labels": {"a": "1", "b": "2"},
            "x": "keep",
            "y": "new",
        }

    def test_dollar_rebinds_in_include(self):
        # Go rebinds $ to each execution's data argument
        tpl = (
            '{{ define "t" }}{{ $.name }}{{ end }}'
            '{{ include "t" .Values.img }}'
        )
        out = render_template(tpl, {"Values": {"img": {"name": "n1"}}})
        assert out == "n1"
