#!/usr/bin/env python
"""One process of a multi-process (DCN-style) simtpu run.

Usage: multihost_worker.py PROC_ID NUM_PROCS COORD_PORT OUT_JSON [ENGINE]

ENGINE selects the sharded engine under test: "scan" (default) runs the
serial-equivalent `ShardedEngine`, "rounds" the bulk `ShardedRoundsEngine`
(same-spec pod runs placed in bulk rounds, node axis sharded — the engine
behind the sharded incremental planner).

Each process contributes 4 virtual CPU devices
(--xla_force_host_platform_device_count), joins the cluster through
`simtpu.parallel.mesh.initialize_multihost` (jax.distributed — the DCN
analog; SURVEY.md §2.3/§5 distributed backend), and runs the SAME
simulation SPMD: host-side ingestion/tensorization is deterministic and
replicated, device placement runs once across the global mesh with the
node axis sharded over every process's devices.  Process 0 writes the
placement map to OUT_JSON; the launcher (tests/test_multihost.py)
compares it against a single-process run.
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    proc_id, nproc, port, out_path = (
        int(sys.argv[1]),
        int(sys.argv[2]),
        sys.argv[3],
        sys.argv[4],
    )
    engine = sys.argv[5] if len(sys.argv) > 5 else "scan"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=4"
        ).strip()
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import jax

    # a sitecustomize may have pre-imported jax pinned to an accelerator
    # platform; the platform must be (re)set before any device use
    jax.config.update("jax_platforms", "cpu")

    from simtpu.api import simulate
    from simtpu.parallel import ShardedEngine, ShardedRoundsEngine
    from simtpu.parallel.mesh import initialize_multihost
    from simtpu.synth import synth_apps, synth_cluster
    from simtpu.workloads.expand import seed_name_hashes

    mesh = initialize_multihost(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=nproc,
        process_id=proc_id,
    )
    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.devices()) == 4 * nproc, len(jax.devices())

    cluster = synth_cluster(
        11, seed=21, zones=3, taint_frac=0.2, gpu_frac=0.3, storage_frac=0.3
    )
    apps = synth_apps(
        40,
        seed=22,
        zones=3,
        pods_per_deployment=8,
        selector_frac=0.3,
        toleration_frac=0.2,
        anti_affinity_frac=0.4,
        gpu_frac=0.2,
        storage_frac=0.2,
    )
    seed_name_hashes(0)
    engine_cls = {"scan": ShardedEngine, "rounds": ShardedRoundsEngine}[engine]
    result = simulate(
        cluster,
        apps,
        extended_resources=("open-local", "gpu"),
        engine_factory=lambda t: engine_cls(t, mesh),
    )
    placements = {}
    for status in result.node_status:
        for pod in status.pods:
            meta = pod["metadata"]
            placements[f"{meta.get('namespace')}/{meta['name']}"] = pod["spec"][
                "nodeName"
            ]
    if proc_id == 0:
        with open(out_path, "w") as f:
            json.dump(
                {
                    "placements": placements,
                    "unscheduled": len(result.unscheduled_pods),
                    "process_count": jax.process_count(),
                    "global_devices": len(jax.devices()),
                    "engine": engine,
                },
                f,
            )
    jax.distributed.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
