#!/usr/bin/env python
"""Seeded concurrent load generator + robustness smoke for `simtpu serve`
(ISSUE 14 satellite; `make bench-serve` runs `--smoke --json`).

Owns a real daemon subprocess (`python -m simtpu.cli serve`) unless
pointed at a running one with --url, then fires a seeded mixed burst —
coalescible drain/resilience queries, one over-deadline request, one
malformed request, and an overload tail past the admission queue — and
reads the daemon's own /metrics registry to report:

    serve_qps             completed queries / burst wall
    serve_p50_s / serve_p99_s   burst latency quantiles
    serve_coalesce_ratio  coalesced / sweep-shaped requests
    serve_requests / serve_coalesced / serve_sweeps / serve_shed /
    serve_timeouts        raw counter deltas

With --smoke the run ASSERTS the robustness matrix end to end on the
subprocess daemon: coalescing counters moved, the over-deadline request
answered a structured 504 while its peers completed, the malformed
request answered 400, the overload tail drew 429s with Retry-After and
zero effect on admitted work, kill -9 + restart rehydrated the session
bit-identically from --state-dir, and SIGTERM drained to a clean exit 0.
Any violated assertion exits 1 (the finding IS the failure).

Stdlib only — the generator must not need more than the daemon does.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time


def request(base, method, path, body=None, timeout=300):
    host, port = base
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request(
            method, path,
            json.dumps(body) if body is not None else None,
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        doc = json.loads(resp.read())
        return resp.status, doc, dict(resp.getheaders())
    finally:
        conn.close()


class Daemon:
    """One owned `simtpu serve` subprocess."""

    def __init__(self, state_dir: str, queue_depth: int, argv_extra=()):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # the generator lives next to the simtpu package — make the
        # daemon subprocess importable from ANY cwd, installed or not
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = (
            repo + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else repo
        )
        self.proc = subprocess.Popen(
            [
                sys.executable, "-m", "simtpu.cli", "serve",
                "--port", "0", "--state-dir", state_dir,
                "--queue-depth", str(queue_depth),
                *argv_extra,
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
        self.port = None
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            line = self.proc.stdout.readline()
            if not line:
                if self.proc.poll() is not None:
                    raise RuntimeError("daemon died during startup")
                time.sleep(0.05)
                continue
            if "listening on http://" in line:
                self.port = int(line.rsplit(":", 1)[1].split()[0])
                break
        if self.port is None:
            raise RuntimeError("daemon never printed its address")
        self.base = ("127.0.0.1", self.port)

    def kill9(self):
        self.proc.kill()
        self.proc.wait(30)

    def sigterm_and_wait(self) -> tuple:
        self.proc.send_signal(signal.SIGTERM)
        rc = self.proc.wait(120)
        return rc, self.proc.stdout.read()


def serve_metrics(base) -> dict:
    _, doc, _ = request(base, "GET", "/metrics")
    return {
        k: v for k, v in doc["metrics"].items() if k.startswith("serve.")
    }


def grow_metrics(base) -> dict:
    """The warm-engine counter family (grow.* + serve.warm_*): the
    arrival sweep asserts the common path stayed append-only
    (`grow.retensorize_fallbacks` unmoved)."""
    _, doc, _ = request(base, "GET", "/metrics")
    return {
        k: v for k, v in doc["metrics"].items()
        if k.startswith("grow.") or k.startswith("serve.warm")
        or k == "compile.grow"
    }


def delta(after: dict, before: dict) -> dict:
    out = {}
    for k, v in after.items():
        b = before.get(k, 0)
        out[k] = v - b if isinstance(v, (int, float)) and isinstance(b, (int, float)) else v
    return out


def quantile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def run_burst(base, sid, n_nodes, burst, threads, seed, say):
    """The seeded mixed burst: coalescible sweeps + one over-deadline +
    one malformed, `threads`-wide.  Returns (results, latencies, wall)."""
    rng = random.Random(seed)
    jobs = []
    for i in range(burst):
        if rng.random() < 0.8:
            jobs.append(("drain", {
                "nodes": [rng.randrange(n_nodes)],
            }))
        else:
            jobs.append(("resilience", {"spec": "k=1"}))
    # the two adversarial riders, at seeded positions
    jobs.insert(rng.randrange(len(jobs)), ("drain", {
        "nodes": [0], "deadline_s": 0.0, "_expect": 504,
    }))
    jobs.insert(rng.randrange(len(jobs)), ("drain", {
        "nodes": ["no-such-node"], "_expect": 400,
    }))
    results = [None] * len(jobs)
    latencies = []
    lat_lock = threading.Lock()
    cursor = {"i": 0}
    cursor_lock = threading.Lock()

    retries = {"n": 0}

    def worker():
        while True:
            with cursor_lock:
                i = cursor["i"]
                if i >= len(jobs):
                    return
                cursor["i"] = i + 1
            kind, payload = jobs[i]
            expect = payload.pop("_expect", 200)
            t0 = time.perf_counter()
            budget = time.monotonic() + 120
            while True:
                status, doc, headers = request(
                    base, "POST", f"/v1/sessions/{sid}/{kind}", payload
                )
                if status != 429 or time.monotonic() >= budget:
                    break
                # a well-behaved client honors the shed: back off for
                # Retry-After and resubmit — admission control degrades
                # arrival rate, not outcomes
                with lat_lock:
                    retries["n"] += 1
                time.sleep(
                    min(float(headers.get("Retry-After", 1)), 0.5)
                )
            dt = time.perf_counter() - t0
            results[i] = (expect, status, doc)
            if expect == 200 and status == 200:
                with lat_lock:
                    latencies.append(dt)

    t0 = time.perf_counter()
    pool = [threading.Thread(target=worker) for _ in range(threads)]
    for t in pool:
        t.start()
    for t in pool:
        t.join()
    wall = time.perf_counter() - t0
    say(
        f"burst: {len(jobs)} queries over {threads} threads in {wall:.2f}s "
        f"({retries['n']} shed-retries honored)"
    )
    return results, sorted(latencies), wall


def overload_tail(base, sid, n_nodes, width, say):
    """Fire `width` drains at once against a small admission queue;
    report (ok_count, shed_responses)."""
    results = [None] * width

    def fire(i):
        try:
            results[i] = request(
                base, "POST", f"/v1/sessions/{sid}/drain",
                {"nodes": [i % n_nodes]},
            )
        except OSError as exc:
            # a refused/reset connection under deliberate overload is a
            # shed-shaped outcome, not a generator crash
            results[i] = (0, {"error": str(exc)}, {})

    pool = [threading.Thread(target=fire, args=(i,)) for i in range(width)]
    for t in pool:
        t.start()
    for t in pool:
        t.join()
    ok = [r for r in results if r[0] == 200]
    shed = [r for r in results if r[0] == 429]
    say(f"overload tail: {len(ok)} served, {len(shed)} shed (429)")
    return ok, shed


def fit_payload(i: int) -> dict:
    """One of two fixed fit-query shapes (alternating): a serving mix
    repeats shapes, which is exactly what the warm engine's append-only
    vocabulary is built for — after the first occurrence of each shape
    the session must answer with ZERO re-tensorization."""
    shape = i % 2
    name = f"arrival-{shape}"
    return {
        "workloads": [{
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {
                "replicas": 1 + shape,
                "template": {
                    "metadata": {"labels": {"app": name}},
                    "spec": {"containers": [{
                        "name": "c", "image": "nginx",
                        "resources": {"requests": {
                            "cpu": "250m" if shape else "100m",
                            "memory": "128Mi",
                        }},
                    }]},
                },
            },
        }],
    }


def arrival_sweep(base, sid, rates, duration, say):
    """Sustained OPEN-LOOP arrival sweep: for each rate, fit queries fire
    at fixed inter-arrival periods for `duration` seconds regardless of
    completions (each request on its own thread — a slow server builds a
    queue instead of slowing the generator, the way real arrival streams
    behave).  Returns per-rate latency records.  The sweep measures WARM
    serving: one fit per shape runs serially first so trace/compile
    cost (paid once per session, docs/serving.md) stays out of the
    latency quantiles."""
    for i in range(2):
        status, _doc, _ = request(
            base, "POST", f"/v1/sessions/{sid}/fit", fit_payload(i)
        )
        if status != 200:
            say(f"arrival warm-up query {i} answered {status}")
    records = []
    for rate in rates:
        period = 1.0 / rate
        lats, statuses = [], []
        lock = threading.Lock()
        threads = []

        def fire(i):
            t0 = time.perf_counter()
            status, doc, _ = request(
                base, "POST", f"/v1/sessions/{sid}/fit", fit_payload(i)
            )
            dt = time.perf_counter() - t0
            with lock:
                statuses.append(status)
                if status == 200:
                    lats.append(dt)

        t_start = time.perf_counter()
        i = 0
        while True:
            t_next = t_start + i * period
            now = time.perf_counter()
            if t_next >= t_start + duration:
                break
            if now < t_next:
                time.sleep(t_next - now)
            th = threading.Thread(target=fire, args=(i,))
            th.start()
            threads.append(th)
            i += 1
        for th in threads:
            th.join()
        wall = time.perf_counter() - t_start
        lats.sort()
        rec = {
            "rate": rate,
            "sent": i,
            "ok": sum(1 for s in statuses if s == 200),
            "shed": sum(1 for s in statuses if s == 429),
            "achieved_qps": round(len(lats) / wall, 2) if wall > 0 else 0.0,
            "p50_s": round(quantile(lats, 0.50), 4),
            "p99_s": round(quantile(lats, 0.99), 4),
        }
        records.append(rec)
        say(
            f"arrival {rate:g}/s: sent={rec['sent']} ok={rec['ok']} "
            f"achieved={rec['achieved_qps']}/s p50={rec['p50_s']}s "
            f"p99={rec['p99_s']}s"
        )
    return records


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--config", default="examples/simtpu-config.yaml")
    ap.add_argument("--state-dir", default="",
                    help="daemon state dir (default: a temp dir)")
    ap.add_argument("--url", default="",
                    help="target a running daemon (host:port) instead of "
                    "owning a subprocess; disables the kill/SIGTERM checks")
    ap.add_argument("--burst", type=int, default=24)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--queue-depth", type=int, default=4,
                    help="owned daemon's admission bound (small so the "
                    "overload tail actually sheds; default 4)")
    ap.add_argument("--smoke", action="store_true",
                    help="assert the full robustness matrix (kill -9 "
                    "restart recovery + SIGTERM drain included)")
    ap.add_argument("--arrival-sweep", default="",
                    help="comma list of sustained open-loop fit-query "
                    "arrival rates (QPS), e.g. '4,12'; asserts p50/p99 "
                    "bounds and zero warm-path retensorize fallbacks")
    ap.add_argument("--arrival-duration", type=float, default=3.0,
                    help="seconds per arrival rate (default 3)")
    ap.add_argument("--p99-max", type=float, default=5.0,
                    help="p99 latency bound asserted at the LOWEST "
                    "arrival rate (default 5s)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    say = (lambda m: print(m, file=sys.stderr, flush=True)) if args.json \
        else (lambda m: print(m, flush=True))
    checks = {}
    failures = []

    def check(name, ok, detail=""):
        checks[name] = bool(ok)
        if not ok:
            failures.append(f"{name}: {detail}")
            say(f"FAIL {name}: {detail}")
        else:
            say(f"ok   {name}")

    state_dir = args.state_dir
    if not state_dir:
        import tempfile

        state_dir = tempfile.mkdtemp(prefix="simtpu-serve-loadgen-")
    daemon = None
    if args.url:
        host, port = args.url.replace("http://", "").split(":")
        base = (host, int(port))
    else:
        say("starting daemon...")
        daemon = Daemon(state_dir, args.queue_depth)
        base = daemon.base
    summary = {}
    try:
        status, doc, _ = request(
            base, "POST", "/v1/sessions", {"config": args.config}
        )
        if status not in (200, 201):
            raise RuntimeError(f"session create failed: {status} {doc}")
        sid, n_nodes = doc["session"], doc["nodes"]
        say(f"session {sid}: {n_nodes} nodes, {doc['pods']} pods")

        before = serve_metrics(base)
        results, lats, wall = run_burst(
            base, sid, n_nodes, args.burst, args.threads, args.seed, say
        )
        after = serve_metrics(base)
        d = delta(after, before)
        sweep_requests = max(
            int(d.get("serve.requests", 0)) - 2, 1
        )  # minus the deadline/malformed riders
        summary = {
            "serve_qps": round(len(lats) / wall, 2) if wall > 0 else 0.0,
            "serve_p50_s": round(quantile(lats, 0.50), 4),
            "serve_p99_s": round(quantile(lats, 0.99), 4),
            "serve_requests": int(d.get("serve.requests", 0)),
            "serve_coalesced": int(d.get("serve.coalesced", 0)),
            "serve_sweeps": int(d.get("serve.sweeps", 0)),
            "serve_shed": int(d.get("serve.shed", 0)),
            "serve_timeouts": int(d.get("serve.timeouts", 0)),
            "serve_coalesce_ratio": round(
                int(d.get("serve.coalesced", 0)) / sweep_requests, 4
            ),
        }

        # burst verdicts: every job answered its expected status
        mis = [
            (expect, status)
            for expect, status, _ in results
            if status != expect
        ]
        check("burst_statuses", not mis, f"mismatches: {mis[:5]}")
        deadline_docs = [
            doc for expect, status, doc in results
            if expect == 504 and status == 504
        ]
        check(
            "deadline_structured_504",
            deadline_docs and all(
                d.get("error") == "deadline" and "partial" in d
                for d in deadline_docs
            ),
            f"got {deadline_docs!r}",
        )
        check(
            "coalescing_happened",
            summary["serve_coalesced"] > 0
            and summary["serve_sweeps"] < sweep_requests,
            f"coalesced={summary['serve_coalesced']} "
            f"sweeps={summary['serve_sweeps']} vs {sweep_requests} requests",
        )

        # sustained open-loop arrival sweep (fit queries, warm path)
        if args.arrival_sweep:
            rates = [float(r) for r in args.arrival_sweep.split(",") if r]
            gbefore = grow_metrics(base)
            records = arrival_sweep(
                base, sid, rates, args.arrival_duration, say
            )
            gafter = grow_metrics(base)
            gd = delta(gafter, gbefore)
            summary["arrival"] = records
            summary["serve_fit_p50_s"] = records[0]["p50_s"]
            summary["serve_fit_p99_s"] = records[0]["p99_s"]
            summary["serve_warm_fits"] = int(gd.get("serve.warm_fits", 0))
            summary["serve_warm_fallbacks"] = int(
                gd.get("grow.retensorize_fallbacks", 0)
            )
            check(
                "arrival_statuses",
                all(r["ok"] + r["shed"] == r["sent"] for r in records),
                f"non-200/429s: {records}",
            )
            check(
                "arrival_low_rate_unshed",
                records[0]["shed"] == 0 and records[0]["ok"] > 0,
                f"sheds at the lowest rate: {records[0]}",
            )
            check(
                "arrival_p99_bound",
                records[0]["p99_s"] <= args.p99_max,
                f"p99 {records[0]['p99_s']}s > {args.p99_max}s "
                f"at {records[0]['rate']:g}/s",
            )
            if gd.get("serve.warm_fits", 0) > 0:
                # the acceptance bar: a repeating serving mix must ride
                # the append-only vocabulary — zero re-tensorizations
                check(
                    "warm_zero_fallbacks",
                    gd.get("grow.retensorize_fallbacks", 0) == 0,
                    f"retensorize fallbacks on the common path: {gd}",
                )

        # overload tail (only meaningful against our own small queue)
        if daemon is not None:
            ok, shed = overload_tail(
                base, sid, n_nodes, width=4 * args.queue_depth, say=say
            )
            check("overload_sheds_429", len(shed) > 0, "no 429 seen")
            check(
                "shed_carries_retry_after",
                all("Retry-After" in h for _, _, h in shed),
                "missing Retry-After header",
            )
            check(
                "admitted_work_unharmed",
                all(doc.get("ok") for _, doc, _ in ok) and len(ok) > 0,
                "an admitted query failed",
            )

        if args.smoke and daemon is not None:
            # kill -9 + restart: the session rehydrates bit-identically
            status, before_doc, _ = request(
                base, "POST", f"/v1/sessions/{sid}/drain", {"nodes": [0]}
            )
            check("pre_kill_drain", status == 200, f"{status}")
            say("kill -9 ...")
            daemon.kill9()
            daemon = Daemon(state_dir, args.queue_depth)
            base = daemon.base
            status, summary_doc, _ = request(
                base, "GET", f"/v1/sessions/{sid}"
            )
            check(
                "recovered_session",
                status == 200 and summary_doc.get("recovered") is True,
                f"{status} {summary_doc}",
            )
            status, after_doc, _ = request(
                base, "POST", f"/v1/sessions/{sid}/drain", {"nodes": [0]}
            )
            check(
                "recovery_bit_identical",
                status == 200 and after_doc == before_doc,
                f"before={before_doc} after={after_doc}",
            )
            # SIGTERM: graceful drain, clean exit 0
            rc, out = daemon.sigterm_and_wait()
            daemon = None
            check(
                "sigterm_clean_exit",
                rc == 0 and "drained" in out,
                f"rc={rc} out={out[-200:]!r}",
            )
    except RuntimeError as exc:
        # daemon startup/session failures (e.g. a starved CI box blowing
        # the boot budget) must still produce the structured JSON verdict
        # the caller parses, never a bare traceback
        check("driver", False, str(exc))
    finally:
        if daemon is not None:
            daemon.kill9()

    summary["ok"] = not failures
    summary["checks"] = checks
    if args.json:
        print(json.dumps(summary))
    else:
        for k, v in summary.items():
            say(f"{k}: {v}")
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
