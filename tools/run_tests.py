#!/usr/bin/env python
"""Full-suite test runner with per-module process isolation.

Why not plain `pytest tests/`: a single long pytest process accumulates
every compiled XLA:CPU executable the suite creates, and past ~190 tests
this host's XLA:CPU `backend_compile_and_load` starts segfaulting (round-4
verdict, weak #1; `simtpu/cache.py` documents the sibling fault on the
cached-executable loader).  Each test module passes in isolation, so the
canonical full run executes one pytest subprocess per module — the same
isolation pytest-forked would give, without the dependency — and
aggregates the results.  The analog of the reference's suite gate
(`Makefile:24-25`, `go test ./...`).

Usage:
    python tools/run_tests.py              # full suite, every module
    python tools/run_tests.py --fast      # skip tests marked `slow`
    python tools/run_tests.py -k PATTERN  # forwarded to pytest
Exit status: 0 iff every module's pytest exits 0 (or 5 = nothing
collected, which --fast can legitimately produce).

Hang safety (ISSUE 6): any test running longer than --timeout seconds
(SIMTPU_TEST_TIMEOUT, default 1200) makes pytest's faulthandler dump
every thread's stack to the module's captured output, and a module still
alive 25% past the budget is killed with whatever it printed — a hung
tier-1 run produces STACKS, never a silent kill.

Span observability (ISSUE 8): with SIMTPU_TRACE=1 each module
subprocess arms the simtpu span tracer and exports its Chrome trace to a
temp file at exit (obs/trace.py init_from_env); the runner aggregates
every module's spans and prints the top-10 slowest span names — where
the suite's wall-clock goes INSIDE the engine, not just per module.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _aggregate_spans(trace_paths):
    """name -> [count, total_s, max_s] over every module's exported
    Chrome trace (missing/corrupt files are skipped — a module that
    crashed before its atexit export must not hide the others)."""
    agg = {}
    for path in trace_paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        for ev in doc.get("traceEvents", ()):
            if ev.get("ph") != "X":
                continue
            row = agg.setdefault(ev["name"], [0, 0.0, 0.0])
            row[0] += 1
            row[1] += ev.get("dur", 0) / 1e6
            row[2] = max(row[2], ev.get("dur", 0) / 1e6)
    return agg


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true", help="deselect @pytest.mark.slow tests")
    ap.add_argument("-k", default=None, help="forwarded to pytest -k")
    ap.add_argument(
        "--timeout",
        type=float,
        default=float(os.environ.get("SIMTPU_TEST_TIMEOUT", 1200)),
        help="per-test faulthandler stack-dump budget in seconds; the "
        "module subprocess is killed at 1.25x this (0 = no timeout)",
    )
    ap.add_argument("modules", nargs="*", help="module paths (default: tests/test_*.py)")
    args = ap.parse_args()

    modules = args.modules or sorted(glob.glob(os.path.join(REPO, "tests", "test_*.py")))
    if not modules:
        print("no test modules found", file=sys.stderr)
        return 2

    extra = []
    if args.fast:
        extra += ["-m", "not slow"]
    if args.k:
        extra += ["-k", args.k]
    if args.timeout > 0:
        # pytest's built-in faulthandler plugin: a test exceeding the
        # budget dumps EVERY thread's stack into the module's output (the
        # hang evidence), without killing the run — the subprocess kill
        # below is the backstop
        extra += ["-o", f"faulthandler_timeout={args.timeout:g}"]

    # SIMTPU_TRACE=1: every module subprocess exports its span trace to a
    # temp file (obs/trace.py: SIMTPU_TRACE=<path> arms + atexit-exports)
    # for the slowest-spans summary after the run
    span_tracing = os.environ.get("SIMTPU_TRACE", "") == "1"
    trace_dir = tempfile.mkdtemp(prefix="simtpu-trace-") if span_tracing else None
    trace_paths = []

    totals = {"passed": 0, "failed": 0, "errors": 0, "skipped": 0, "deselected": 0}
    failures = []
    timings = []  # (seconds, module) for the slowest-modules summary
    t_all = time.perf_counter()
    for mod in modules:
        rel = os.path.relpath(mod, REPO)
        env = None
        if span_tracing:
            tpath = os.path.join(
                trace_dir, os.path.basename(rel) + ".trace.json"
            )
            trace_paths.append(tpath)
            env = dict(os.environ, SIMTPU_TRACE=tpath)
        t0 = time.perf_counter()
        timed_out = False
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "pytest", rel, "-q", "--no-header", *extra],
                cwd=REPO,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                env=env,
                timeout=args.timeout * 1.25 if args.timeout > 0 else None,
            )
            out, rc = proc.stdout, proc.returncode
        except subprocess.TimeoutExpired as exc:
            # the faulthandler dump (armed at 1x the budget) is already in
            # the captured output — surface it instead of a silent kill
            out = exc.stdout or ""
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
            rc, timed_out = -1, True
        dt = time.perf_counter() - t0
        timings.append((dt, rel))
        tail = out.strip().splitlines()
        summary = tail[-1] if tail else ""
        if timed_out:
            summary = (
                f"TIMEOUT after {dt:.0f}s (faulthandler stacks in the "
                f"module output below; budget {args.timeout:g}s/test)"
            )
        for key in totals:
            # pytest prints singular forms too ("1 error in 0.5s")
            m = re.search(rf"(\d+) {key.rstrip('s')}s?", summary)
            if m:
                totals[key] += int(m.group(1))
        ok = rc in (0, 5)  # 5: no tests collected (e.g. --fast)
        print(f"{'ok  ' if ok else 'FAIL'} {rel:42s} {dt:7.1f}s  {summary}", flush=True)
        if not ok:
            failures.append(rel)
            # keep the evidence: everything pytest printed for the module
            print(out, flush=True)
    wall = time.perf_counter() - t_all
    print(
        f"\n== {totals['passed']} passed, {totals['failed']} failed, "
        f"{totals['errors']} errors, {totals['skipped']} skipped, "
        f"{totals['deselected']} deselected in {wall:.1f}s "
        f"across {len(modules)} modules =="
    )
    # where the suite's wall-clock goes — the target list for anyone
    # shaving CI time (or spotting a module whose runtime regressed)
    slowest = sorted(timings, reverse=True)[:10]
    if slowest:
        print("slowest modules:")
        for dt, rel in slowest:
            print(f"  {dt:7.1f}s  {rel}  ({dt / max(wall, 1e-9) * 100:.0f}%)")
    if span_tracing:
        # ... and where it goes INSIDE the engine: the top-10 slowest
        # span names aggregated over every module's exported trace
        # (obs/trace.py; ISSUE 8)
        agg = _aggregate_spans(trace_paths)
        rows = sorted(agg.items(), key=lambda kv: -kv[1][1])[:10]
        if rows:
            print("slowest spans (SIMTPU_TRACE=1, all modules):")
            for name, (count, total_s, max_s) in rows:
                print(
                    f"  {total_s:8.2f}s  {name:24s} x{count}  "
                    f"(max {max_s:.3f}s)"
                )
    if failures:
        print("failing modules: " + ", ".join(failures))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
