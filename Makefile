# Build/test entry points — the analog of the reference's Makefile
# (`/root/reference/Makefile:24-25`, `go test ./...`).  simtpu is pure
# Python + a self-building ctypes extension, so there is no build step;
# `install` wires an editable checkout, `test` is the CI gate.

PY ?= python

.PHONY: all install lint test test-all test-perf bench bench-cold bench-faults bench-layout bench-durable bench-audit bench-solve bench-obs bench-explain bench-multihost bench-serve bench-timeline bench-scan bench-grow fuzz-smoke clean

all: test

install:
	$(PY) -m pip install -e .

# static-analysis gate: compileall catches parse/syntax errors everywhere,
# then ruff (config-minimal, [tool.ruff] in pyproject.toml) enforces the
# pyflakes/pycodestyle core.  ruff is a test-extra (`pip install -e
# ".[test]"` — CI installs it); on hosts without it the syntax gate still
# runs and the skip is announced rather than silent.
lint:
	$(PY) -m compileall -q simtpu tools tests bench.py __graft_entry__.py
	@if $(PY) -m ruff --version >/dev/null 2>&1; then \
		$(PY) -m ruff check .; \
	else \
		echo "ruff not installed (pip install -e '.[test]'); syntax gate only"; \
	fi

# fast tier: every module, slow-marked tests deselected (<10 min target)
test: lint
	$(PY) tools/run_tests.py --fast

# the full suite, one subprocess per module (see tools/run_tests.py for
# why plain `pytest tests/` cannot be the canonical entry on CPU hosts)
test-all: lint
	$(PY) tools/run_tests.py

# dedicated perf runs: wall-clock envelopes armed (idle host required)
test-perf:
	SIMTPU_PERF_ASSERT=1 $(PY) tools/run_tests.py

bench:
	$(PY) bench.py

# cold-start smoke at a small shape with the persistent compilation cache
# OFF: every executable really compiles, so the JSON line's expand/
# tensorize/compile/first-dispatch breakdown (and compile wall < serial
# overlap) measures the AOT pipeline itself, not cache reads.  Compare
# against SIMTPU_BENCH_PRECOMPILE=0 for the serialized-compile baseline.
bench-cold:
	SIMTPU_COMPILATION_CACHE=off SIMTPU_BENCH_NODES=2000 \
	SIMTPU_BENCH_PODS=20000 SIMTPU_BENCH_SMALL=0 SIMTPU_BENCH_HARD=0 \
	SIMTPU_BENCH_MATRIX=0 SIMTPU_BENCH_PLAN=0 SIMTPU_BENCH_BIG=0 \
	$(PY) bench.py

# fault-injection smoke at a small shape (mirrors bench-cold): exhaustive
# single-node scenario sweep through the batched engine vs the serial
# drain/requeue replay floor, plus a small N+k plan_resilience search —
# fault_scenarios_per_s / fault_sweep_speedup land in the JSON line
bench-faults:
	SIMTPU_BENCH_FAULTS=1 SIMTPU_BENCH_NODES=2000 SIMTPU_BENCH_PODS=20000 \
	SIMTPU_BENCH_SMALL=0 SIMTPU_BENCH_HARD=0 SIMTPU_BENCH_MATRIX=0 \
	SIMTPU_BENCH_PLAN=0 SIMTPU_BENCH_BIG=0 $(PY) bench.py

# carried-state layout smoke at a small shape (mirrors bench-cold): the
# compact-vs-dense A/B point alone, ASSERTING bit-identical placements and
# a >= 2x carried-byte reduction on the multi-domain synthetic cluster —
# state_bytes / state_bytes_dense / state_compact_ratio land in the JSON
# line (CI runs this alongside lint + the fast tier)
bench-layout:
	SIMTPU_BENCH_LAYOUT=1 SIMTPU_BENCH_LAYOUT_ASSERT=1 \
	SIMTPU_BENCH_LAYOUT_NODES=2000 SIMTPU_BENCH_LAYOUT_PODS=20000 \
	SIMTPU_BENCH_NODES=500 SIMTPU_BENCH_PODS=2000 \
	SIMTPU_BENCH_SCAN_PODS=200 SIMTPU_BENCH_BASELINE_PODS=50 \
	SIMTPU_BENCH_SMALL=0 SIMTPU_BENCH_HARD=0 SIMTPU_BENCH_MATRIX=0 \
	SIMTPU_BENCH_PLAN=0 SIMTPU_BENCH_BIG=0 SIMTPU_BENCH_FAULTS=0 \
	$(PY) bench.py

# durable-execution smoke (mirrors bench-layout): checkpoint a small
# incremental plan, kill it mid-search, resume, and ASSERT the resumed
# PlanResult is bit-identical to the uninterrupted run; plus an injected
# RESOURCE_EXHAUSTED on the bulk dispatcher asserting the chunk-halving
# backoff converges with identical placements — durable_* and
# backoff_events land in the JSON line (CI runs this alongside the fast
# tier)
bench-durable:
	SIMTPU_BENCH_DURABLE=1 SIMTPU_BENCH_DURABLE_ASSERT=1 \
	SIMTPU_BENCH_NODES=500 SIMTPU_BENCH_PODS=2000 \
	SIMTPU_BENCH_SCAN_PODS=200 SIMTPU_BENCH_BASELINE_PODS=50 \
	SIMTPU_BENCH_SMALL=0 SIMTPU_BENCH_HARD=0 SIMTPU_BENCH_MATRIX=0 \
	SIMTPU_BENCH_PLAN=0 SIMTPU_BENCH_BIG=0 SIMTPU_BENCH_FAULTS=0 \
	SIMTPU_BENCH_LAYOUT=0 $(PY) bench.py

# trust-but-verify smoke (mirrors bench-durable): mutation-kill every
# corruption class ASSERTING 100% auditor detection, plus a small
# incremental plan with the auditor auto-on asserting a clean verdict and
# < 10% audit overhead — audit_s / audit_violations / audit_kill_rate
# land in the JSON line (CI runs this alongside the fast tier)
bench-audit:
	SIMTPU_BENCH_AUDIT=1 SIMTPU_BENCH_AUDIT_ASSERT=1 \
	SIMTPU_BENCH_NODES=500 SIMTPU_BENCH_PODS=2000 \
	SIMTPU_BENCH_SCAN_PODS=200 SIMTPU_BENCH_BASELINE_PODS=50 \
	SIMTPU_BENCH_SMALL=0 SIMTPU_BENCH_HARD=0 SIMTPU_BENCH_MATRIX=0 \
	SIMTPU_BENCH_PLAN=0 SIMTPU_BENCH_BIG=0 SIMTPU_BENCH_FAULTS=0 \
	SIMTPU_BENCH_LAYOUT=0 SIMTPU_BENCH_DURABLE=0 $(PY) bench.py

# global-solver backend smoke (mirrors bench-audit): one solver consult
# vs the exact doubling+bisection on a solver-eligible aligned mix,
# ASSERTING bit-identical certified node counts, clean audits on both
# answers, and accept rate > 0 — solve_speedup / solve_accept_rate /
# solve_status land in the JSON line (CI runs this alongside the fast
# tier; the >= 2x speedup claim is measured at the 2k-node default
# shape, recorded not asserted at this CI smoke shape)
bench-solve:
	SIMTPU_BENCH_SOLVE=1 SIMTPU_BENCH_SOLVE_ASSERT=1 \
	SIMTPU_BENCH_SOLVE_NODES=100 SIMTPU_BENCH_SOLVE_PODS=6000 \
	SIMTPU_BENCH_SOLVE_MAX_NEW=256 \
	SIMTPU_BENCH_NODES=500 SIMTPU_BENCH_PODS=2000 \
	SIMTPU_BENCH_SCAN_PODS=200 SIMTPU_BENCH_BASELINE_PODS=50 \
	SIMTPU_BENCH_SMALL=0 SIMTPU_BENCH_HARD=0 SIMTPU_BENCH_MATRIX=0 \
	SIMTPU_BENCH_PLAN=0 SIMTPU_BENCH_BIG=0 SIMTPU_BENCH_FAULTS=0 \
	SIMTPU_BENCH_LAYOUT=0 SIMTPU_BENCH_DURABLE=0 SIMTPU_BENCH_AUDIT=0 \
	$(PY) bench.py

# observability overhead gate (mirrors bench-audit): the same warm bulk
# placement with the span tracer off vs on, ASSERTING < 3% tracing-on
# overhead, zero-overhead no-op spans when disabled, bit-identical
# placements, and a Perfetto-valid exported trace file —
# obs_overhead_pct / obs_spans / obs_trace_valid land in the JSON line
# (CI runs this alongside the fast tier)
bench-obs:
	SIMTPU_BENCH_OBS=1 SIMTPU_BENCH_OBS_ASSERT=1 \
	SIMTPU_BENCH_OBS_NODES=2000 SIMTPU_BENCH_OBS_PODS=20000 \
	SIMTPU_BENCH_NODES=500 SIMTPU_BENCH_PODS=2000 \
	SIMTPU_BENCH_SCAN_PODS=200 SIMTPU_BENCH_BASELINE_PODS=50 \
	SIMTPU_BENCH_SMALL=0 SIMTPU_BENCH_HARD=0 SIMTPU_BENCH_MATRIX=0 \
	SIMTPU_BENCH_PLAN=0 SIMTPU_BENCH_BIG=0 SIMTPU_BENCH_FAULTS=0 \
	SIMTPU_BENCH_LAYOUT=0 SIMTPU_BENCH_DURABLE=0 SIMTPU_BENCH_AUDIT=0 \
	$(PY) bench.py

# decision-observability smoke (mirrors bench-obs): one fuzz-generated
# gnarly case placed with and without the explain pipeline, ASSERTING
# bit-identical placements, per-stage elimination counts that sum to N
# and match the pure-numpy twin, a named binding resource, consistent
# score attribution, and the explain-pass overhead bound —
# explain_s / explain_pods / explain_groups land in the JSON line
# (CI runs this alongside the fast tier)
bench-explain:
	SIMTPU_BENCH_EXPLAIN=1 SIMTPU_BENCH_EXPLAIN_ASSERT=1 \
	SIMTPU_BENCH_NODES=500 SIMTPU_BENCH_PODS=2000 \
	SIMTPU_BENCH_SCAN_PODS=200 SIMTPU_BENCH_BASELINE_PODS=50 \
	SIMTPU_BENCH_SMALL=0 SIMTPU_BENCH_HARD=0 SIMTPU_BENCH_MATRIX=0 \
	SIMTPU_BENCH_PLAN=0 SIMTPU_BENCH_BIG=0 SIMTPU_BENCH_FAULTS=0 \
	SIMTPU_BENCH_LAYOUT=0 SIMTPU_BENCH_DURABLE=0 SIMTPU_BENCH_AUDIT=0 \
	SIMTPU_BENCH_OBS=0 $(PY) bench.py

# multihost bench-point smoke: the `--multihost` launcher end to end at a
# tiny shape — a fresh 8-forced-host-device subprocess places the
# north-star mix through the GSPMD ShardedRoundsEngine, ASSERTING record
# schema + pod accounting + the publish round-trip into a scratch
# BASELINE (vs_target recomputed by the one documented formula, no warm
# number from a single run). The full-shape run behind BASELINE.json's
# `published` block is this same path at default knobs with --publish.
bench-multihost:
	SIMTPU_BENCH_MULTIHOST_ASSERT=1 \
	SIMTPU_BENCH_MULTIHOST_NODES=200 SIMTPU_BENCH_MULTIHOST_PODS=1000 \
	SIMTPU_BENCH_PODS_PER_DEP=50 \
	$(PY) bench.py --multihost

# long-lived service smoke (ISSUE 14, mirrors bench-durable): drive
# tools/serve_loadgen.py --smoke against a real `simtpu serve`
# subprocess — seeded mixed burst, ASSERTING the robustness matrix:
# request coalescing counters moved (serve.coalesced > 0, fewer sweep
# dispatches than requests), an over-deadline request answered a
# structured 504 while peers completed, the overload tail drew 429s with
# Retry-After and zero effect on admitted work, kill -9 + restart
# rehydrated the session bit-identically from the checkpoint, and
# SIGTERM drained to a clean exit 0 —
# serve_qps / serve_coalesce_ratio / serve_p99_s land in the JSON line
bench-serve:
	SIMTPU_BENCH_SERVE=1 SIMTPU_BENCH_SERVE_ASSERT=1 \
	SIMTPU_BENCH_NODES=500 SIMTPU_BENCH_PODS=2000 \
	SIMTPU_BENCH_SCAN_PODS=200 SIMTPU_BENCH_BASELINE_PODS=50 \
	SIMTPU_BENCH_SMALL=0 SIMTPU_BENCH_HARD=0 SIMTPU_BENCH_MATRIX=0 \
	SIMTPU_BENCH_PLAN=0 SIMTPU_BENCH_BIG=0 SIMTPU_BENCH_FAULTS=0 \
	SIMTPU_BENCH_LAYOUT=0 SIMTPU_BENCH_DURABLE=0 SIMTPU_BENCH_AUDIT=0 \
	SIMTPU_BENCH_OBS=0 SIMTPU_BENCH_EXPLAIN=0 $(PY) bench.py

# trace-driven timeline smoke (ISSUE 15, mirrors bench-serve): a seeded
# small-shape arrival stream (gangs, CronJob firings, node events,
# elastic HPA jobs) replayed through simtpu/timeline, ASSERTING the
# batched path's end state (planes, placement log, landing vectors,
# event timestamps) is bit-identical to the serial one-event-at-a-time
# oracle, the auditor certified both, the sim clock is monotone, and the
# timeline.* registry counters moved — timeline_events_per_s /
# timeline_pending_p50_s / timeline_preemptions land in the JSON line
bench-timeline:
	SIMTPU_BENCH_TIMELINE=1 SIMTPU_BENCH_TIMELINE_ASSERT=1 \
	SIMTPU_BENCH_TIMELINE_NODES=16 SIMTPU_BENCH_TIMELINE_PODS=360 \
	SIMTPU_BENCH_TIMELINE_DAYS=0.2 \
	SIMTPU_BENCH_NODES=500 SIMTPU_BENCH_PODS=2000 \
	SIMTPU_BENCH_SCAN_PODS=200 SIMTPU_BENCH_BASELINE_PODS=50 \
	SIMTPU_BENCH_SMALL=0 SIMTPU_BENCH_HARD=0 SIMTPU_BENCH_MATRIX=0 \
	SIMTPU_BENCH_PLAN=0 SIMTPU_BENCH_BIG=0 SIMTPU_BENCH_FAULTS=0 \
	SIMTPU_BENCH_LAYOUT=0 SIMTPU_BENCH_DURABLE=0 SIMTPU_BENCH_AUDIT=0 \
	SIMTPU_BENCH_OBS=0 SIMTPU_BENCH_EXPLAIN=0 SIMTPU_BENCH_SERVE=0 \
	$(PY) bench.py

# round-16 scan/delta perf-lever smoke (mirrors bench-timeline): the
# all-heavy storage+GPU+ports wavefront A/B (bit-identical, accepts > 0,
# >= 1.5x the pod-at-a-time floor), the direct compact-delta evict/
# restore churn (counter-pinned, bit-identical, beats the expand ->
# apply -> recompress round trip), and a small timeline replay pinned
# bit-identical across SIMTPU_DELTA_DIRECT=1/0 — scan_smoke_* land in
# the JSON line (CI runs this alongside the fast tier)
bench-scan:
	SIMTPU_BENCH_SCAN_SMOKE=1 SIMTPU_BENCH_SCAN_SMOKE_ASSERT=1 \
	SIMTPU_BENCH_NODES=500 SIMTPU_BENCH_PODS=2000 \
	SIMTPU_BENCH_SCAN_PODS=200 SIMTPU_BENCH_BASELINE_PODS=50 \
	SIMTPU_BENCH_SMALL=0 SIMTPU_BENCH_HARD=0 SIMTPU_BENCH_MATRIX=0 \
	SIMTPU_BENCH_PLAN=0 SIMTPU_BENCH_BIG=0 SIMTPU_BENCH_FAULTS=0 \
	SIMTPU_BENCH_LAYOUT=0 SIMTPU_BENCH_DURABLE=0 SIMTPU_BENCH_AUDIT=0 \
	SIMTPU_BENCH_OBS=0 SIMTPU_BENCH_EXPLAIN=0 SIMTPU_BENCH_SERVE=0 \
	SIMTPU_BENCH_TIMELINE=0 $(PY) bench.py

# round-20 warm-engine serving smoke (mirrors bench-scan): append-only
# vocabulary growth A/B — warm grow-engine waves vs re-tensorize+replay
# (bit-identical, zero rebuilds, recompiles bounded by the pow2 buckets
# touched) and the in-process warm-vs-cold serve fit QPS comparison
# (>= 10x, zero retensorize fallbacks on the warm mix) — grow_* land in
# the JSON line (CI runs this alongside the fast tier)
bench-grow:
	SIMTPU_BENCH_GROW=1 SIMTPU_BENCH_GROW_ASSERT=1 \
	SIMTPU_BENCH_NODES=500 SIMTPU_BENCH_PODS=2000 \
	SIMTPU_BENCH_SCAN_PODS=200 SIMTPU_BENCH_BASELINE_PODS=50 \
	SIMTPU_BENCH_SMALL=0 SIMTPU_BENCH_HARD=0 SIMTPU_BENCH_MATRIX=0 \
	SIMTPU_BENCH_PLAN=0 SIMTPU_BENCH_BIG=0 SIMTPU_BENCH_FAULTS=0 \
	SIMTPU_BENCH_LAYOUT=0 SIMTPU_BENCH_DURABLE=0 SIMTPU_BENCH_AUDIT=0 \
	SIMTPU_BENCH_OBS=0 SIMTPU_BENCH_EXPLAIN=0 SIMTPU_BENCH_SERVE=0 \
	SIMTPU_BENCH_TIMELINE=0 SIMTPU_BENCH_SCAN_SMOKE=0 $(PY) bench.py

# differential fuzz over the fixed seed corpus at small shapes, across
# the FULL engine-config matrix — 8 forced host devices arm the
# GSPMD-sharded cell on CPU-only CI runners (the conftest trick); any
# divergence from the serial baseline or dirty audit fails the target
# with a shrunk reproducer YAML left under /tmp/simtpu-fuzz
fuzz-smoke:
	XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m simtpu.cli fuzz --cases 6 --nodes 12 --pods 48 --seed 0 \
	--out /tmp/simtpu-fuzz --json

clean:
	rm -rf build dist *.egg-info simtpu/native/_build
	find . -name __pycache__ -type d -exec rm -rf {} +
